"""Process wiring: the factory graph + manager, and a test/dev environment.

reference: cmd/controller/main.go:40-77 — flags, scheme, manager, cloud
provider registry, producer/metrics-client/autoscaler factories, controller
registration. KarpenterRuntime is that wiring; Environment adds the
envtest-analog conveniences the reference's pkg/test/environment provides
(isolated store+registry, converge helper).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.autoscaler import BatchAutoscaler
from karpenter_tpu.cloudprovider import Options as CloudOptions
from karpenter_tpu.cloudprovider import registry as provider_registry
from karpenter_tpu.controllers import (
    HorizontalAutoscalerController,
    Manager,
    MetricsProducerController,
    ScalableNodeGroupController,
)
from karpenter_tpu.metrics.clients import MetricsClientFactory
from karpenter_tpu.metrics.producers import ProducerFactory
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.store import Store


@dataclass
class Options:
    """reference: main.go:40-46 (minus ports, which live in observability)."""

    prometheus_uri: Optional[str] = None  # None = in-process registry client
    cloud_provider: Optional[str] = None  # None = env/default (not-implemented)
    solver_uri: Optional[str] = None  # host:port of a solver sidecar
    # (sidecar/client.py); None = in-process device solve
    data_dir: Optional[str] = None  # WAL+snapshot dir; None = in-memory only
    verbose: bool = False
    # opt-in consolidation engine (karpenter_tpu/consolidation): batched
    # node-drain planning + cordon→verify→drain actuation through the
    # ScalableNodeGroup controller. Off by default: draining nodes is a
    # disruptive posture an operator must choose (--consolidate).
    consolidate: bool = False
    # solver hot-path tuning (docs/solver-service.md "Latency tuning"):
    # the MAX coalescing window (adaptive: an idle queue dispatches
    # immediately) and the in-flight dispatch cap (1 = double-buffered
    # pipeline, 0 = serial)
    solver_window_s: float = 0.002
    solver_pipeline_depth: int = 1
    # sharded dispatch (docs/solver-service.md "Sharded dispatch"):
    # requests whose pods x groups cell count reaches the threshold ride
    # the multi-device mesh; 0 disables. shard_devices caps the mesh
    # device count (None = every visible device); shard_mesh_shape pins
    # explicit (pods, groups) extents instead of the pods-major
    # factorization.
    solver_shard_threshold: int = 1 << 24
    solver_shard_devices: Optional[int] = None
    solver_shard_mesh: Optional[tuple] = None
    # device-resident fleet state (docs/solver-service.md
    # "Device-resident fleet state"): singleton solve dispatches keep
    # their operand stack resident on device and churn applies as
    # batched scatter updates — bit-identical to the re-upload path,
    # so ON by default; False pins the upload-every-dispatch posture
    # (the bench-resident OFF arm and an operator escape hatch).
    solver_resident: bool = True
    # degradation-ladder tuning (docs/resilience.md):
    # engine requeue backoff under retryable failures — first retry in
    # ~[base, 3*base], monotone up to the cap
    backoff_base_s: float = 1.0
    backoff_cap_s: float = 60.0
    # per-node-group actuation circuit breaker: consecutive provider
    # failures before opening, and the open window before a half-open
    # probe reconcile is admitted
    circuit_failure_threshold: int = 5
    circuit_reset_s: float = 120.0
    # solver backend health FSM: consecutive device failures before a
    # wholesale trip to numpy, probe cadence while degraded, and the
    # hung-worker watchdog timeout (0 disables the watchdog)
    solver_health_threshold: int = 3
    solver_probe_interval_s: float = 5.0
    solver_watchdog_timeout_s: float = 30.0
    # predictive scaling (docs/forecasting.md): metric-history ring
    # capacity per series, and how old a history sample may be and still
    # stand in for a FAILED live metric query (the stale-metric bridge;
    # 0 disables reuse). Forecasting itself is opt-in per HA via
    # spec.behavior.forecast — these knobs size the shared machinery.
    forecast_history: int = 64
    stale_metric_max_age_s: float = 60.0
    # opt-in preemption engine (karpenter_tpu/preemption,
    # docs/preemption.md): batched eviction planning for high-priority
    # pending pods + budgeted eviction actuation, coordinated with
    # consolidation. Off by default: evicting workloads is a disruptive
    # posture an operator must choose (--preempt).
    preempt: bool = False
    # default max concurrent evictions charged against one node group
    # per hold window (120s; spec.eviction_budget overrides per group)
    preempt_budget: int = 1
    # fleet default priority for pods naming an unknown PriorityClass
    # (--default-priority): feeds the census encoder AND the engines
    default_pod_priority: int = 0
    # crash-safe controller state (karpenter_tpu/recovery,
    # docs/resilience.md "Crash recovery"): directory for the
    # protective-state journal + checkpoints + fence generation. None =
    # ephemeral (a restart cold-starts FSMs/holds/budgets/backoff/
    # forecast state and actuation is unfenced — the pre-PR-7 posture).
    journal_dir: Optional[str] = None
    # full manager ticks a RECOVERED boot holds the warm-up: no
    # consolidation or preemption planning until this many reconcile
    # passes have confirmed fleet state (first boots skip it)
    recovery_warmup_ticks: int = 1
    # cost model knobs (karpenter_tpu/cost, docs/cost.md): price for
    # catalog-unknown instance types and the spot-tier multiplier. The
    # subsystem itself is opt-in per HA (spec.behavior.slo) and per
    # node group (spec.warmPool) — these size the shared pricing only.
    cost_default_hourly: float = 1.0
    cost_spot_multiplier: float = 0.35
    # pluggable pricing feed (cost/pricing.py, docs/cost.md): a
    # JSON/YAML catalog file reloaded on mtime change, consulted before
    # the built-in catalog. None = built-in catalog only.
    pricing_file: Optional[str] = None
    # simulation seed (--sim-seed, docs/simulator.md): one seed threaded
    # through every SEEDED SimLab scenario's RNG streams. None = each
    # scenario's pinned default, keeping replay digests byte-identical.
    sim_seed: Optional[int] = None
    # multi-tenant control plane (karpenter_tpu/tenancy,
    # docs/multitenancy.md): path to a tenant-config file (--tenant-
    # config). None = single-tenant, byte-identical to the pre-tenancy
    # wiring; set, the runtime builds a TenantRegistry of namespaced
    # per-cluster stacks and a MultiTenantScheduler batching
    # cross-tenant work through the one shared SolverService.
    tenant_config: Optional[str] = None
    # tenant-weighted solve deadlines (docs/multitenancy.md): bounds a
    # deferred tenant's wall-clock wait behind earlier admission
    # rounds — each tenant's budget is this many seconds scaled by
    # weight / mean weight; an exhausted budget serves the tenant
    # immediately from the bit-identical mirror and counts a deferral.
    # None = unbounded wait (fairness still bounds rows per round).
    tenant_deadline_s: Optional[float] = None
    # this control plane's OWN tenant id (--tenant-id): stamped as gRPC
    # metadata on every sidecar RPC so a SHARED solver sidecar can
    # attribute traffic per tenant (the other multi-tenant topology:
    # many control-plane processes, one solver service). None = no
    # metadata, the single-tenant wire.
    tenant_id: Optional[str] = None
    # decision provenance ledger (observability/provenance.py,
    # docs/observability.md "Decision provenance"): record the full
    # input chain behind every HA decision into a bounded columnar ring
    # (/debug/decisions, JSONL next to --trace-export). Default OFF,
    # matching tracing's posture: provenance is telemetry, and the off
    # path must stay mark-free (property-pinned byte-identical).
    provenance: bool = False
    # control-plane self-SLO monitor (observability/selfslo.py): the
    # e2e-latency objective (seconds against the
    # karpenter_reconcile_e2e_seconds histogram — pick a bucket bound)
    # and the SLO target the multi-window burn rates measure against.
    selfslo_objective_s: float = 1.0
    selfslo_target: float = 0.99
    # solver introspection plane (observability/devicetelemetry.py,
    # docs/observability.md "Device telemetry & introspection"):
    # compile ledger + compile-storm trips, device memory telemetry +
    # the self-SLO memory source, XLA cost attribution on dispatch
    # spans, /debug/solver. Default OFF, matching tracing/provenance:
    # the off path is property-pinned byte-identical and mark-free.
    introspect: bool = False
    # compile-cache misses inside ONE tick window (after steady state)
    # that count as a compile storm, and the bytes_in_use/bytes_limit
    # ratio that trips the device-memory high watermark
    introspect_storm_threshold: int = 4
    introspect_memory_watermark: float = 0.9
    # event-driven reconcile (docs/solver-service.md "Event-driven
    # reconcile"): watch events schedule debounced coalesced event
    # passes over the dirty keys, demoting the periodic tick to a
    # resync backstop. Off by default — the tick-paced loop is
    # byte-identical with the flag absent (--event-driven). The
    # debounce window bounds solve amplification under event storms:
    # everything landing inside one window rides ONE pass.
    event_driven: bool = False
    event_debounce_s: float = 0.05
    # INTERNAL (simulate + tests): False runs NO debounce thread — the
    # harness drives Manager.run_event_pass itself on the scripted
    # clock, keeping replays deterministic. The CLI never sets this.
    event_thread: bool = True
    # boot-time compile-cache pre-warm (docs/solver-service.md
    # "Compile pre-warm"): compile the smallest bucket rungs of the
    # always-on kernel families (solve + decide) before the first
    # event arrives, so a cold plane's first event pass doesn't eat a
    # first-touch jit compile (hotpath BASELINE: idle p99 533 ms vs
    # p50 30 ms). Skipped per rung when the compile cache already
    # hits; the persistent cache (KARPENTER_COMPILE_CACHE) turns the
    # remaining cost into a disk read.
    prewarm_compile: bool = False
    # fused steady-state tick (ops/fusedtick.py, docs/solver-service.md
    # "Fused tick"): route the batched autoscaler's forecast -> decide
    # -> cost chain through ONE compiled program per tenant batch
    # (SolverService.fused_tick) instead of 3+ per-stage dispatches
    # with host round-trips between them. Default OFF — the unfused
    # wire stays byte-identical; --fused-tick (or --profile
    # production) turns it on. Outputs are property-pinned bitwise
    # equal to the chained path, so this is latency-only.
    fused_tick: bool = False
    # persistent compile cache directory (--compile-cache-dir): the
    # first-class promotion of the KARPENTER_COMPILE_CACHE env var,
    # matching the sidecar's flag of the same name. Set, jit compiles
    # taking >=1s persist to disk and a restarted process reloads them
    # instead of recompiling (utils/backend.configure_compile_cache).
    # None = env var only (the pre-flag wire).
    compile_cache_dir: Optional[str] = None
    # joint pool-group allocation (karpenter_tpu/poolgroups,
    # docs/poolgroups.md): PoolGroup CRDs name member autoscalers with
    # cross-pool ratio bands and shared budgets; the engine excludes
    # members from the independent cost ladders and refines them in ONE
    # joint dispatch (SolverService.poolgroup). Default OFF — with the
    # flag absent (or no PoolGroup objects) the wire is byte-identical
    # to the uncoordinated plane (--poolgroups).
    poolgroups: bool = False
    # replicated control plane (karpenter_tpu/replication,
    # docs/resilience.md "Replicated control plane"): partition tenants
    # across N leader-elected replicas with fenced handoff. partitions=0
    # (default) builds NOTHING — no lease objects, no lease traffic, no
    # replica metrics: the single-replica wire is byte-identical, per
    # the tracing/provenance/introspection off-path precedent.
    partitions: int = 0
    # this replica's identity on the lease plane (--replica-id); None =
    # a generated karpenter-<hex> identity (fine for a single process,
    # useless for operators correlating /debug/replicas across a fleet)
    replica_id: Optional[str] = None
    # partition/heartbeat lease duration in seconds (--lease-duration):
    # the failover detection horizon — a dead replica's tenants are
    # adoptable one lease duration (plus skew tolerance) after its last
    # renew
    lease_duration_s: float = 15.0


class KarpenterRuntime:
    def __init__(
        self,
        options: Optional[Options] = None,
        store: Optional[Store] = None,
        registry: Optional[GaugeRegistry] = None,
        cloud_provider_factory=None,
        clock=None,
    ):
        import time as _time

        options = options or Options()
        self.options = options
        self.clock = clock or _time.time
        self._owns_store = store is None
        self.store = store if store is not None else self._open_store(options)
        self.registry = registry if registry is not None else GaugeRegistry()

        # persistent compile cache, armed BEFORE anything can compile
        # (the cache singleton latches at first compile): the embedded
        # Options path mirrors __main__'s flag/env resolution so a
        # runtime built in-process (tests, library use) gets the same
        # restart-warm compiles as the CLI.
        if options.compile_cache_dir:
            from karpenter_tpu.utils.backend import configure_compile_cache

            configure_compile_cache(options.compile_cache_dir)

        self._bind_observability(options)

        # crash-safe state subsystem (karpenter_tpu/recovery): built
        # FIRST — it claims the fence generation durably before anything
        # can actuate, and replays the protective-state journal the
        # subsystems below restore from
        self.recovery = self._build_recovery(options)

        self.cloud_provider = (
            cloud_provider_factory
            if cloud_provider_factory is not None
            else provider_registry.new_factory(
                CloudOptions(store=self.store), provider=options.cloud_provider
            )
        )
        self._seed_fence_validator()
        device_solver, decider = self._build_solver_client(options)
        # ALL bin-pack callers route through the shared solve service
        # (solver/service.py): coalescing, shape-bucketed compile cache,
        # backpressure + numpy fallback, and a metrics surface in THIS
        # runtime's registry so /metrics exposes it with no extra wiring.
        # Under the gRPC split the service fronts the sidecar client —
        # queueing/deadlines/fallback still apply, device math does not
        # return to this process.
        from karpenter_tpu.solver import SolverService

        self.solver_service = SolverService(
            registry=self.registry,
            window_s=options.solver_window_s,
            pipeline_depth=options.solver_pipeline_depth,
            device_solver=device_solver,
            decider=decider,
            health_failure_threshold=options.solver_health_threshold,
            health_probe_interval_s=options.solver_probe_interval_s,
            watchdog_timeout_s=options.solver_watchdog_timeout_s,
            shard_threshold=options.solver_shard_threshold,
            shard_devices=options.solver_shard_devices,
            shard_mesh_shape=options.solver_shard_mesh,
            resident=options.solver_resident,
        )
        # the solver introspection plane (observability/devicetelemetry
        # .py): ALWAYS built — a disabled plane is one attribute read
        # per hook, the provenance posture — and enabled by
        # --introspect. Attached to the service so dispatch sites note
        # compile misses; evaluated once per manager tick (_on_tick).
        from karpenter_tpu.observability import SolverIntrospection

        self.solver_introspection = SolverIntrospection(
            enabled=options.introspect,
            registry=self.registry,
            clock=self.clock,
            recorder=self.flight_recorder,
            storm_threshold=options.introspect_storm_threshold,
            watermark=options.introspect_memory_watermark,
        ).attach(self.solver_service)
        self._reset_caches_for_recovery()
        self.producer_factory = ProducerFactory(
            self.store, self.cloud_provider, registry=self.registry,
            solver=self.solver_service.solve,
            default_priority=options.default_pod_priority,
        )
        # predictive scaling (forecast/, docs/forecasting.md): history,
        # skill gating, and the batched forecast riding the solve
        # service's queue/compile-cache/FSM; the metrics clients feed
        # the query-keyed warm pool through the observer hook
        from karpenter_tpu.forecast import FleetForecaster

        self.forecaster = FleetForecaster(
            forecast_fn=self.solver_service.forecast,
            registry=self.registry,
            clock=self.clock,
            capacity=options.forecast_history,
            stale_max_age_s=options.stale_metric_max_age_s,
        )
        self._attach_recovery_forecast()
        self.metrics_clients = MetricsClientFactory(
            registry=self.registry, prometheus_uri=options.prometheus_uri,
            observer=self.forecaster.observe_query,
        )
        # cost/SLO subsystem (cost/, docs/cost.md): the multi-objective
        # refinement of the fleet decide through SolverService.cost and
        # the forecast-risk-sized warm pools it signals. Always built —
        # an SLO-free fleet pays one list comprehension per tick and
        # decisions stay bit-identical (the engine's zero-overhead
        # opt-out contract).
        from karpenter_tpu.cost import (
            CostEngine,
            CostModel,
            WarmPoolEngine,
            pricing_source_for,
        )

        self.cost_model = CostModel(
            default_hourly=options.cost_default_hourly,
            spot_multiplier=options.cost_spot_multiplier,
            pricing=pricing_source_for(options.pricing_file),
        )
        self.cost_engine = CostEngine(
            store=self.store,
            cost_fn=self.solver_service.cost,
            model=self.cost_model,
            forecaster=self.forecaster,
            registry=self.registry,
        )
        # joint pool-group allocation (--poolgroups, poolgroups/,
        # docs/poolgroups.md): built only under the flag — the absent
        # engine keeps the autoscaler wire byte-identical
        self.pool_engine = None
        headroom_source = self.cost_engine.headroom
        if options.poolgroups:
            from karpenter_tpu.poolgroups import PoolGroupEngine

            self.pool_engine = PoolGroupEngine(
                store=self.store,
                poolgroup_fn=self.solver_service.poolgroup,
                model=self.cost_model,
                forecaster=self.forecaster,
                registry=self.registry,
            )

            def headroom_source(ns, name, _cost=self.cost_engine.headroom,
                                _pool=self.pool_engine.headroom):
                # warm pools size from the WORST risk either refiner
                # sees for the target group
                return max(_cost(ns, name), _pool(ns, name))

        self.warmpool = WarmPoolEngine(
            headroom_source=headroom_source,
            registry=self.registry,
        )
        self.batch_autoscaler = BatchAutoscaler(
            self.metrics_clients, self.store, clock=self.clock,
            decider=self.solver_service.decide,
            forecaster=self.forecaster,
            cost_engine=self.cost_engine,
            pool_engine=self.pool_engine,
            tenant=options.tenant_id,
            # --fused-tick: the forecast -> decide -> cost chain rides
            # ONE compiled program per batch through the service's
            # fused seam (same FSM/ledger/never-block ladder). None
            # keeps the chained per-stage wire byte-identical.
            fused_tick_fn=(
                self.solver_service.fused_tick
                if options.fused_tick else None
            ),
        )
        self._build_disruption_engines(options)
        # Registration order = in-tick evaluation order. Producers run first
        # so signals are fresh, then node groups observe, then the batched
        # autoscaler decides — one tick moves a signal end to end (the
        # reference's produce→scrape→poll chain costs up to 20s of interval
        # latency; SURVEY.md §6).
        backoff_journal = None
        if self.recovery is not None:
            backoff_journal = self.recovery.handle("backoff")
        # the composed hook: recovery bookkeeping + the self-SLO
        # evaluation, both once per manager tick (_on_tick)
        tick_hook = self._on_tick
        self._sng_controller = ScalableNodeGroupController(
            self.cloud_provider, consolidator=self.consolidation,
            preemptor=self.preemption,
            warmpool=self.warmpool,
            registry=self.registry,
            circuit_failure_threshold=options.circuit_failure_threshold,
            circuit_reset_s=options.circuit_reset_s,
            clock=self.clock,
            recovery=self.recovery,
        )
        self.manager = Manager(
            self.store, clock=self.clock, registry=self.registry,
            solver_service=self.solver_service,
            backoff_base_s=options.backoff_base_s,
            backoff_cap_s=options.backoff_cap_s,
            tick_hook=tick_hook,
            recovery_journal=backoff_journal,
            event_driven=options.event_driven,
            event_debounce_s=options.event_debounce_s,
            event_thread=options.event_thread,
        ).register(
            MetricsProducerController(self.producer_factory),
            self._sng_controller,
            HorizontalAutoscalerController(
                self.batch_autoscaler, solver_service=self.solver_service
            ),
        )
        self._build_tenancy(options)
        self._build_replication(options)
        self._build_selfslo(options)
        self._finish_recovery_boot()
        self._maybe_prewarm(options)

    def _build_disruption_engines(self, options: Options) -> None:
        """The opt-in disruption engines (consolidation + preemption),
        coordinated both ways: preemption skips consolidation's
        in-flight nodes, and consolidation's candidate gate consults
        preemption's holds (node_guard). Their gauges land in THIS
        runtime's registry."""
        self.consolidation = None
        if options.consolidate:
            from karpenter_tpu.consolidation import ConsolidationEngine

            self.consolidation = ConsolidationEngine(
                self.store,
                solver_service=self.solver_service,
                registry=self.registry,
                clock=self.clock,
            )
            self._attach_recovery_engine(
                "consolidation", self.consolidation
            )
        # preemption engine (opt-in): batched eviction planning through
        # SolverService.preempt, actuating budgeted evictions through
        # the store; coordinated BOTH ways with consolidation — it
        # skips consolidation's in-flight nodes, and consolidation's
        # candidate gate consults its holds (node_guard)
        self.preemption = None
        if options.preempt:
            from karpenter_tpu.preemption import (
                PreemptionConfig,
                PreemptionEngine,
            )

            self.preemption = PreemptionEngine(
                self.store,
                solver_service=self.solver_service,
                consolidation=self.consolidation,
                registry=self.registry,
                config=PreemptionConfig(
                    budget_per_group=options.preempt_budget,
                    default_priority=options.default_pod_priority,
                ),
                clock=self.clock,
            )
            self._attach_recovery_engine("preemption", self.preemption)
            if self.consolidation is not None:
                self.consolidation.node_guard = (
                    self.preemption.active_nodes
                )

    def _maybe_prewarm(self, options: Options) -> None:
        """Boot-time compile pre-warm (docs/solver-service.md "Compile
        pre-warm"), run LAST: the warm-up drives real (tiny) dispatches
        through the fully-wired service, so it must not race recovery
        restore or observe a half-built runtime."""
        if options.prewarm_compile:
            families = ("solve", "decide")
            if options.fused_tick:
                # the fused megakernel joins the warm list only when
                # the tick will actually dispatch it
                families += ("fused",)
            self.solver_service.prewarm(families)

    def _build_tenancy(self, options: Options) -> None:
        """Multi-tenant control plane (docs/multitenancy.md): with a
        tenant config, the registry namespaces per-cluster stacks and
        the scheduler batches cross-tenant decide/cost/forecast through
        THIS runtime's shared SolverService. Without one, nothing is
        built and every existing path is byte-identical."""
        self.tenancy = None
        self.tenant_scheduler = None
        if not options.tenant_config:
            return
        from karpenter_tpu.tenancy import (
            MultiTenantScheduler,
            TenantRegistry,
            load_tenant_config,
        )

        specs = load_tenant_config(options.tenant_config)
        self.tenancy = TenantRegistry(
            service=self.solver_service,
            registry=self.registry,
            journal_dir=options.journal_dir,
            clock=self.clock,
            specs=specs,
        )
        self.tenant_scheduler = MultiTenantScheduler(
            self.tenancy, self.solver_service,
            deadline_s=options.tenant_deadline_s,
        )

    def _build_replication(self, options: Options) -> None:
        """Replicated control plane (docs/resilience.md "Replicated
        control plane"): with --partitions, this process becomes one
        leader-elected replica — per-partition CAS leases over the
        store, rendezvous-hash tenant assignment, fenced tenant handoff
        on the per-tenant journal dirs, all advanced once per manager
        tick. partitions=0 (the default) builds nothing: no Lease
        objects, no lease traffic, no karpenter_replica_* gauges — the
        single-replica wire stays byte-identical."""
        self.replication = None
        if options.partitions <= 0:
            return
        from karpenter_tpu.replication import ReplicatedControlPlane

        tenants_source = None
        journal_dir_for = None
        if self.tenancy is not None:
            tenants_source = self.tenancy.tenants
            journal_dir_for = self.tenancy.journal_dir_for
        self.replication = ReplicatedControlPlane(
            self.store,
            replica_id=options.replica_id or None,
            partitions=options.partitions,
            lease_duration=options.lease_duration_s,
            tenants_source=tenants_source,
            journal_dir_for=journal_dir_for,
            validator=getattr(
                self.cloud_provider, "fence_validator", None
            ),
            warmup_ticks=options.recovery_warmup_ticks,
            registry=self.registry,
            clock=self.clock,
            recorder=self.flight_recorder,
        )

    @staticmethod
    def _open_store(options: Options):
        from karpenter_tpu.store.persistence import open_store

        return open_store(options.data_dir)

    def _bind_observability(self, options: Options) -> None:
        """Observability wiring (docs/observability.md): the process
        tracer, flight recorder, and decision-provenance ledger publish
        their counters + the karpenter_reconcile_e2e_seconds histogram
        into THIS runtime's registry, and trip-class recorder events
        dump crash-safely into --journal-dir next to the recovery
        journal. The ledger is enabled only under --provenance (and
        never force-disabled here — a test that enabled the process
        default keeps it)."""
        from karpenter_tpu.observability import (
            default_flight_recorder,
            default_ledger,
            default_tracer,
        )

        self.tracer = default_tracer()
        self.tracer.bind_registry(self.registry)
        self.flight_recorder = default_flight_recorder()
        self.flight_recorder.bind_registry(self.registry)
        if options.journal_dir:
            self.flight_recorder.configure(dump_dir=options.journal_dir)
        self.decision_ledger = default_ledger()
        self.decision_ledger.bind_registry(self.registry)
        if options.provenance:
            self.decision_ledger.enabled = True

    def _build_selfslo(self, options: Options) -> None:
        """The control plane's self-SLO monitor (observability/selfslo):
        multi-window burn rates over its OWN e2e-latency histogram plus
        the solver backend FSM and (when multi-tenant) the per-tenant
        breaker board; evaluated once per manager tick via the tick
        hook, served at /debug/selfslo. Always built — one snapshot
        tuple per tick."""
        from karpenter_tpu.observability import SelfSLOMonitor

        tenant_source = None
        if self.tenant_scheduler is not None:
            breakers = self.tenant_scheduler.breakers
            registry = self.tenancy

            def tenant_source():
                return {
                    tenant: breakers.is_open(tenant)
                    for tenant in registry.tenants()
                }

        self.selfslo = SelfSLOMonitor(
            registry=self.registry,
            objective_s=options.selfslo_objective_s,
            target=options.selfslo_target,
            clock=self.clock,
            histogram=self.registry.gauge("reconcile", "e2e_seconds"),
            fsm_source=self.solver_service.backend_health,
            tenant_source=tenant_source,
            # the fourth source (observability/devicetelemetry.py):
            # device-memory high watermark — quiet (None) while the
            # introspection plane is off or the backend reports no
            # memory stats
            memory_source=self.solver_introspection.memory_source,
            # the fifth source (replication/plane.py): lease renew
            # failures / in-flight handoffs burn budget — quiet (None)
            # in the single-replica deployment
            replica_source=(
                self.replication.slo_source
                if self.replication is not None else None
            ),
            recorder=self.flight_recorder,
        )

    def _on_tick(self) -> None:
        """Composed manager tick hook: recovery bookkeeping (warm-up
        countdown, checkpoint cadence), then the solver introspection
        pass (compile-storm window close + device memory poll — it
        must run BEFORE the self-SLO evaluation so the memory source
        reflects THIS tick), then the self-SLO evaluation — the
        monitor must observe the tick INCLUDING any degradation the
        tick just hit."""
        if self.recovery is not None:
            self.recovery.on_tick()
        replication = getattr(self, "replication", None)
        if replication is not None:
            # the lease round + fenced handoffs run BEFORE the self-SLO
            # evaluation so a mid-failover tick burns budget as one
            replication.on_tick()
        introspection = getattr(self, "solver_introspection", None)
        if introspection is not None:
            introspection.on_tick()
        selfslo = getattr(self, "selfslo", None)
        if selfslo is not None:
            selfslo.evaluate()

    def _build_solver_client(self, options: Options):
        """(device_solver, decider) seams for the gRPC process split:
        with a sidecar configured the control-plane process runs NO
        device math — the decision kernel rides the same split."""
        self.solver_client = None
        if not options.solver_uri:
            return None, None
        from karpenter_tpu.sidecar.client import SolverClient

        self.solver_client = SolverClient(
            options.solver_uri, tenant=options.tenant_id
        )
        return self.solver_client.solve, self.solver_client.decide

    def _build_recovery(self, options: Options):
        if not options.journal_dir:
            return None
        from karpenter_tpu.recovery import RecoveryManager

        return RecoveryManager(
            options.journal_dir,
            registry=self.registry,
            clock=self.clock,
            warmup_ticks=options.recovery_warmup_ticks,
        )

    def _seed_fence_validator(self) -> None:
        """Raise the provider's fence floor to this incarnation's
        generation at boot: a stale (restarted-over) incarnation is
        rejected even before our first actuation, and a provider
        factory freshly constructed by a restarted process does not
        start with an empty memory of generations."""
        if self.recovery is None:
            return
        validator = getattr(self.cloud_provider, "fence_validator", None)
        if validator is not None:
            validator.observe(self.recovery.fence.generation)

    def _reset_caches_for_recovery(self) -> None:
        """Recovery boot: identity-keyed PROCESS-LEVEL caches must
        rebuild cold — stale pre-crash entries (the encoder delta
        layer's same-object fast path + its resident scatter plans,
        compiled-program keys, device-resident operand stacks) must not
        be silently reused against post-restart state. This runtime's
        OWN SolverService is freshly constructed (already cold); the
        state that actually survives an in-process restart is the
        module-global encoder delta cache (reset_delta_cache also
        clears the scatter plans) and the process-default solver
        service (reset_caches also drops its ResidentFleetState)
        shared by simulate/sidecar embedders across runtime
        incarnations."""
        if self.recovery is None or not self.recovery.recovered:
            return
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encoder as _encoder,
        )
        from karpenter_tpu.solver.service import (
            reset_default_service_caches,
        )

        _encoder.reset_delta_cache()
        reset_default_service_caches()

    def _attach_recovery_forecast(self) -> None:
        """Forecast state journals (skill EWMAs as sets, history as
        bounded ring appends; the checkpoint stores rings columnar) and
        restores here, so the blend resumes with its earned skill
        instead of a cold start."""
        if self.recovery is None:
            return
        self.forecaster.journal = self.recovery.handle("forecast")
        self.forecaster.history.journal = self.recovery.handle("history")
        self.forecaster.restore_state(
            self.recovery.table("forecast"),
            self.recovery.table("history"),
        )
        self.recovery.register_snapshot(
            "forecast", self.forecaster.snapshot_state
        )
        self.recovery.register_snapshot(
            "history", self.forecaster.history.snapshot_rings
        )

    def _attach_recovery_engine(self, sub: str, engine) -> None:
        """Disruption-engine crash safety: FSM transitions / holds /
        budget charges journal WRITE-AHEAD of the effects they cover, a
        restarted controller restores them (resuming phases instead of
        re-planning disruption), and no planning happens until the
        recovery warm-up confirms fleet state."""
        if self.recovery is None:
            return
        engine.journal = self.recovery.handle(sub)
        engine.disruption_gate = self.recovery.allow_disruption
        engine.restore_state(self.recovery.table(sub))
        self.recovery.register_snapshot(sub, engine.snapshot_state)

    def _finish_recovery_boot(self) -> None:
        """Restore the requeue-backoff ladder (restored due times are
        capped at now + backoff cap) and compact the journal: every
        boot re-bounds it, so a restart storm cannot grow it."""
        if self.recovery is None:
            return
        self.manager.restore_backoff(self.recovery.table("backoff"))
        self.recovery.register_snapshot(
            "backoff", self.manager.snapshot_backoff
        )
        # drop restored breaker/intent state for groups deleted while
        # we were down — no Deleted event will ever fire for them
        self._sng_controller.prune_restored_missing(self.store)
        self.recovery.finish_boot()

    def run(self, duration: float) -> None:
        self.manager.run(duration)

    def close(self) -> None:
        if self.manager is not None:
            self.manager.close()
        if getattr(self, "replication", None) is not None:
            # surrender leases BEFORE the tenancy teardown: successors
            # can start adopting while this process finishes closing
            self.replication.close()
            self.replication = None
        if self.tenancy is not None:
            self.tenancy.close()
            self.tenancy = None
        if self.recovery is not None:
            self.recovery.close()
            self.recovery = None
        if self.solver_service is not None:
            self.solver_service.close()
        if self.solver_client is not None:
            self.solver_client.close()
            self.solver_client = None
        if self._owns_store and hasattr(self.store, "close"):
            self.store.close()
