"""Kubernetes resource.Quantity semantics, reimplemented for the TPU build.

The reference accumulates pod resource requests and node allocatable as
k8s.io/apimachinery resource.Quantity values and renders them into status
strings (reference: pkg/metrics/producers/reservedcapacity/producer.go:63-86,
reservations.go:45-56). Matching its output exactly ("7600m", "77Gi",
"385500Mi", "150") requires the same parse + canonical-format rules, so this
module models the three behaviors we depend on:

- parse of decimal/binary suffixed quantities ("1100m", "25Gi", "99", "128500Mi")
- Add() adopting the other operand's format when the receiver is zero
- String() canonicalization: binary quantities pick the largest power-of-1024
  suffix with an integer mantissa; decimal quantities pick the largest
  power-of-1000 (engineering) exponent with an integer mantissa.

Values are exact (fractions.Fraction); device math uses float arrays converted
via .to_float() / unit helpers, never this class.
"""

from __future__ import annotations

import re
from fractions import Fraction

DECIMAL_SI = "DecimalSI"
BINARY_SI = "BinarySI"
DECIMAL_EXPONENT = "DecimalExponent"

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE])|(?P<exp>[eE][+-]?\d+))?$"
)

# format codes returned by the native parser (karpenter_tpu/native)
_NATIVE_FORMATS = (DECIMAL_SI, BINARY_SI, DECIMAL_EXPONENT)
_native_kicked = False


def _native_parser():
    """The C parser once its background build/load completes, else None
    (pure-Python oracle runs). The first call only KICKS OFF the build in a
    daemon thread — a cold compile never blocks a parse, so e.g. the first
    AdmissionReview a webhook validates is served at Python speed instead
    of waiting on cc."""
    global _native_kicked
    try:
        from karpenter_tpu import native
    except Exception:
        return None
    if not _native_kicked:
        _native_kicked = True
        try:
            native.ensure_kquantity_async()
        except Exception:
            pass
    return native.peek_kquantity()


class Quantity:
    """Exact-arithmetic quantity with a preferred display format."""

    __slots__ = ("value", "format", "_float")

    def __init__(self, value: Fraction | int = 0, format: str = DECIMAL_SI):
        self.value = Fraction(value)
        self.format = format
        self._float: float | None = None  # to_float memo (hot watch path)

    @classmethod
    def parse(cls, s: str) -> "Quantity":
        if isinstance(s, Quantity):
            return Quantity(s.value, s.format)
        if isinstance(s, (int, float)):
            return Quantity(Fraction(s), DECIMAL_SI)
        native = _native_parser()
        if native is not None:
            try:
                num, den, fmt = native.parse(s)
            except ValueError:
                pass  # overflow or unrecognized: the regex path decides
            else:
                q = cls.__new__(cls)
                q.value = Fraction(num, den)
                q.format = _NATIVE_FORMATS[fmt]
                q._float = None
                return q
        m = _QUANTITY_RE.match(s.strip())
        if m is None:
            raise ValueError(f"unable to parse quantity {s!r}")
        num = Fraction(m.group("num"))
        if m.group("sign") == "-":
            num = -num
        suffix = m.group("suffix")
        exp = m.group("exp")
        if suffix in _BINARY_SUFFIXES:
            return cls(num * _BINARY_SUFFIXES[suffix], BINARY_SI)
        if suffix is not None:
            return cls(num * _DECIMAL_SUFFIXES[suffix], DECIMAL_SI)
        if exp is not None:
            return cls(num * Fraction(10) ** int(exp[1:]), DECIMAL_EXPONENT)
        return cls(num, DECIMAL_SI)

    def add(self, other: "Quantity") -> "Quantity":
        # Zero receivers adopt the operand's format, mirroring apimachinery's
        # Quantity.Add — this is what makes an all-Gi accumulation print "77Gi"
        # even though the accumulator started as DecimalSI zero.
        fmt = other.format if self.value == 0 else self.format
        return Quantity(self.value + other.value, fmt)

    def sub(self, other: "Quantity") -> "Quantity":
        fmt = other.format if self.value == 0 else self.format
        return Quantity(self.value - other.value, fmt)

    def to_float(self) -> float:
        # memoized: Quantity is immutable by contract, and the columnar
        # feed calls this for every request of every watch-delivered pod
        # (Fraction->float division is the costly part)
        # getattr default: __new__/deepcopy paths can leave the slot unset
        f = getattr(self, "_float", None)
        if f is None:
            f = self._float = float(self.value)
        return f

    def milli(self) -> int:
        """Value in thousandths, rounded up (k8s MilliValue semantics)."""
        v = self.value * 1000
        return int(v) if v.denominator == 1 else int(v) + (1 if v > 0 else 0)

    def __eq__(self, other) -> bool:
        return isinstance(other, Quantity) and self.value == other.value

    def __lt__(self, other: "Quantity") -> bool:
        return self.value < other.value

    def __le__(self, other: "Quantity") -> bool:
        return self.value <= other.value

    def __hash__(self):
        return hash(self.value)

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"

    def __str__(self) -> str:
        v = self.value
        if v == 0:
            return "0"
        sign = "-" if v < 0 else ""
        if v < 0:
            v = -v
        if self.format == BINARY_SI:
            text = _binary_str(v, sign)
            if text is not None:
                return text
            # fractional binary quantities fall back to milli, like k8s
            # does when forced below base units
        # decimal canonicalization: largest engineering exponent with an
        # integer mantissa
        for suffix in ("E", "P", "T", "G", "M", "k", "", "m", "u", "n"):
            unit = _DECIMAL_SUFFIXES[suffix]
            scaled = v / unit
            if scaled.denominator == 1:
                return f"{sign}{scaled}{suffix}"
        # sub-nano: round up to nano (k8s rounds up when precision is lost)
        scaled = v / _DECIMAL_SUFFIXES["n"]
        return f"{sign}{int(scaled) + 1}n"


def _binary_str(v, sign: str):
    """Canonical binary-SI rendering: the largest Ki..Ei suffix with an
    integer mantissa, else the bare integer; None when v is fractional
    below base units (caller falls back to decimal)."""
    for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        unit = _BINARY_SUFFIXES[suffix]
        if v >= unit and (v / unit).denominator == 1:
            return f"{sign}{v // unit}{suffix}"
    if v.denominator == 1:
        return f"{sign}{v}"
    return None


def parse_quantity(s) -> Quantity:
    return Quantity.parse(s)


def zero() -> Quantity:
    return Quantity(0, DECIMAL_SI)
