"""Small functional helpers (reference: pkg/utils/functional/functional.go:24-91).

merge_into reproduces the JSON-merge defaulting trick the reference uses for
scaling-rule defaults (horizontalautoscaler.go:249-265): fields that are set
(non-None) on src overlay the corresponding fields on dest.
"""

from __future__ import annotations

import dataclasses


def merge_into(dest, *srcs):
    """Overlay non-None dataclass fields of each src onto dest, in order."""
    for src in srcs:
        if src is None:
            continue
        for field in dataclasses.fields(src):
            value = getattr(src, field.name)
            if value is not None:
                setattr(dest, field.name, value)
    return dest


def pad_to_multiple(n: int, bucket: int) -> int:
    """Round n up to a multiple of bucket, with a floor of one bucket.

    The shared padding policy for compiled shapes (solver universes, mesh
    divisibility): sizes GROW to the next bucket so recompiles happen only on
    bucket crossings, and padded slots are masked, never truncated.
    """
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)
