"""Logging setup + invariant helpers (reference: pkg/utils/log/log.go:26-40)."""

from __future__ import annotations

import json
import logging
import sys

_LOGGER = logging.getLogger("karpenter_tpu")


def setup(verbose: bool = False) -> logging.Logger:
    level = logging.DEBUG if verbose else logging.INFO
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
    )
    _LOGGER.handlers[:] = [handler]
    _LOGGER.setLevel(level)
    return _LOGGER


def logger() -> logging.Logger:
    return _LOGGER


class InvariantViolation(AssertionError):
    """Raised for states that validation should have made impossible."""


def invariant_violated(message: str) -> None:
    _LOGGER.error("Invariant violated: %s", message)
    raise InvariantViolation(message)


def pretty(obj) -> str:
    try:
        return json.dumps(obj, indent=2, default=str)
    except TypeError:
        return repr(obj)
