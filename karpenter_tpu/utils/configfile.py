"""One loader for operator-supplied JSON-or-YAML config files.

Three CLI surfaces accept "a JSON or YAML file" (--what-if,
--tenant-config, --pricing-file) and each used to hand-roll the same
try-json-else-yaml sequence; format behavior (encoding, error shape)
now lives here once. JSON is tried first — every JSON document is valid
YAML, but json.loads is the cheaper and stricter parser, and a clear
json error message beats yaml's for the common case.
"""

from __future__ import annotations

from typing import Any


def load_json_or_yaml(path: str) -> Any:
    """Parse `path` as JSON, falling back to YAML. Raises ValueError
    (with the path) when neither parser accepts the content; I/O errors
    propagate as-is."""
    with open(path) as f:
        text = f.read()
    import json

    try:
        return json.loads(text)
    except ValueError:
        pass
    try:
        import yaml

        return yaml.safe_load(text)
    except Exception as error:  # noqa: BLE001 — unified parse error
        raise ValueError(
            f"{path}: neither valid JSON nor YAML ({error})"
        ) from error
