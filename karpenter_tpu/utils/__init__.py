from karpenter_tpu.utils.quantity import Quantity, parse_quantity
from karpenter_tpu.utils.functional import merge_into

__all__ = ["Quantity", "parse_quantity", "merge_into"]
