"""Fast structural clone for API objects — the store's copy primitive.

The store isolates every read/write with a deep copy (store/store.py); at
fleet scale that copy IS the control plane's hottest host path (a 1%-churn
tick over 100k pods performs thousands of pod copies). copy.deepcopy pays
for generality it doesn't need here — memo dicts, reduce/reconstruct
protocol, cycle detection. API objects are trees of dataclasses, builtin
containers, scalars, and immutable leaves, so a direct recursive rebuild
with a per-class field cache is ~10x faster.

Semantics vs copy.deepcopy, by design:
- Quantity instances are SHARED, not copied: Quantity is immutable by
  contract (utils/quantity.py — all arithmetic returns new instances).
- Aliasing inside one object tree is not preserved (each reference is
  cloned independently). API objects are plain trees; nothing relies on
  internal sharing.
- Unknown types fall back to copy.deepcopy, so correctness never depends
  on this module knowing every type.
"""

from __future__ import annotations

import copy
from dataclasses import fields, is_dataclass
from typing import Any, Dict, Tuple

from karpenter_tpu.utils.quantity import Quantity

_ATOMIC = (str, int, float, bool, type(None), bytes, Quantity)

# per-dataclass field-name cache: (names tuple, uses __dict__)
_FIELD_CACHE: Dict[type, Tuple[str, ...]] = {}


def fast_clone(x: Any) -> Any:
    t = x.__class__
    if t in (str, int, float, bool, type(None), bytes, Quantity):
        return x
    if t is dict:
        return {k: fast_clone(v) for k, v in x.items()}
    if t is list:
        return [fast_clone(v) for v in x]
    if t is tuple:
        return tuple(fast_clone(v) for v in x)
    if t is set:
        return {fast_clone(v) for v in x}
    names = _FIELD_CACHE.get(t)
    if names is None:
        if not is_dataclass(x):
            return copy.deepcopy(x)  # unknown type: full generality
        names = tuple(f.name for f in fields(t))
        _FIELD_CACHE[t] = names
    new = object.__new__(t)
    for name in names:
        object.__setattr__(new, name, fast_clone(getattr(x, name)))
    return new
