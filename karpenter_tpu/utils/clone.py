"""Fast structural clone for API objects — the store's copy primitive.

The store isolates every read/write with a deep copy (store/store.py); at
fleet scale that copy IS the control plane's hottest host path (a 1%-churn
tick over 100k pods performs thousands of pod copies). copy.deepcopy pays
for generality it doesn't need here — memo dicts, reduce/reconstruct
protocol, cycle detection. API objects are trees of dataclasses, builtin
containers, scalars, and immutable leaves, so a direct recursive rebuild
is ~10x faster, and a COMPILED per-dataclass cloner (straight-line field
assignments generated on first use) removes the per-field loop overhead
on top of that.

Semantics vs copy.deepcopy, by design:
- Quantity instances are SHARED, not copied: Quantity is immutable by
  contract (utils/quantity.py — all arithmetic returns new instances).
- Aliasing inside one object tree is not preserved (each reference is
  cloned independently). API objects are plain trees; nothing relies on
  internal sharing.
- Unknown types fall back to copy.deepcopy, so correctness never depends
  on this module knowing every type.
"""

from __future__ import annotations

import copy
from dataclasses import fields, is_dataclass
from typing import Any, Callable, Dict

from karpenter_tpu.utils.quantity import Quantity


def _identity(x: Any) -> Any:
    return x


def fast_clone(x: Any) -> Any:
    cloner = _CLONERS.get(x.__class__)
    if cloner is None:
        cloner = _register_cloner(x.__class__)
    return cloner(x)


def _clone_dict(x: dict) -> dict:
    return {k: fast_clone(v) for k, v in x.items()}


def _clone_list(x: list) -> list:
    return [fast_clone(v) for v in x]


def _clone_tuple(x: tuple) -> tuple:
    return tuple(fast_clone(v) for v in x)


def _clone_set(x: set) -> set:
    return {fast_clone(v) for v in x}


# exact-class dispatch (subclasses take the registration path, so e.g. a
# dict subclass is NOT silently flattened to a plain dict)
_CLONERS: Dict[type, Callable[[Any], Any]] = {
    str: _identity,
    int: _identity,
    float: _identity,
    bool: _identity,
    type(None): _identity,
    bytes: _identity,
    Quantity: _identity,  # immutable by contract: shared
    dict: _clone_dict,
    list: _clone_list,
    tuple: _clone_tuple,
    set: _clone_set,
}


def _register_cloner(cls: type) -> Callable[[Any], Any]:
    """First encounter of a class: compile a straight-line cloner for
    dataclasses (frozen ones assign via object.__setattr__, same trick
    dataclasses' own __init__ uses), fall back to copy.deepcopy for
    anything else."""
    if is_dataclass(cls):
        names = tuple(f.name for f in fields(cls))
        frozen = cls.__dataclass_params__.frozen
        assign = (
            (lambda n: f"    _set(n, {n!r}, _c(x.{n}))")
            if frozen
            else (lambda n: f"    n.{n} = _c(x.{n})")
        )
        lines = [
            "def _cloner(x, _new=object.__new__, _cls=_CLS, _c=fast_clone,"
            " _set=object.__setattr__):",
            "    n = _new(_cls)",
            *[assign(name) for name in names],
            "    return n",
        ]
        namespace = {"_CLS": cls, "fast_clone": fast_clone, "object": object}
        exec("\n".join(lines), namespace)  # noqa: S102 — own class metadata
        cloner = namespace["_cloner"]
    else:
        cloner = copy.deepcopy
    _CLONERS[cls] = cloner
    return cloner
