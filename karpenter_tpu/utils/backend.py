"""Backend selection helpers for non-driver processes.

This container's sitecustomize (axon) imports jax at interpreter startup
with JAX_PLATFORMS=axon, and initializing the axon TPU client from a
non-driver process can hang or raise UNAVAILABLE. Tests and the multi-chip
dryrun therefore run on a virtual multi-device CPU backend. The sequence is
subtle enough that it lives here once, shared by tests/conftest.py and
__graft_entry__.dryrun_multichip:

- mutating os.environ["JAX_PLATFORMS"] is too late (jax already imported);
  platform selection must go through jax.config;
- XLA_FLAGS *is* read lazily at CPU client creation, so the env var works
  for the device count — but only if set before the first backend init.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu(n_devices: int) -> None:
    """Make the CPU backend the jax default with n_devices virtual devices.

    Must run before the first backend init (do NOT call jax.devices() or
    run any computation first — on this container that triggers the hanging
    axon init). Safe to call repeatedly; an existing device-count flag with
    a smaller count is replaced so a later caller asking for more devices
    is not silently truncated (which would fail mesh construction).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if match is None:
        flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
    elif int(match.group(1)) < n_devices:
        flags = (
            flags[: match.start()]
            + f"{_COUNT_FLAG}={n_devices}"
            + flags[match.end() :]
        )
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses we spawn

    import jax

    jax.config.update("jax_platforms", "cpu")


def probe_default_backend(
    timeout: float = 120.0,
    retries: int = 2,
    hang_schedule: tuple = (),
):
    """Probe the DEFAULT jax backend in a subprocess, with retry+backoff.

    The axon TPU client can raise UNAVAILABLE or HANG at init (the round-1
    bench artifact was erased by exactly this), so the probe runs out of
    process under a hard timeout, where both failure modes are
    recoverable. Returns (device_count, "") on a healthy backend, else
    (0, reason). Never initializes a backend in THIS process.

    A raised UNAVAILABLE often clears within seconds, so those retry on
    the short exponential-backoff schedule. A HANG means the tunnel is
    down and has never been observed to clear quickly — by default it
    aborts the remaining short retries so control-plane entry points fall
    back to CPU fast. Callers that would rather wait out a tunnel outage
    (the benchmark: a CPU number is near-worthless evidence) pass
    ``hang_schedule``, extra delays in seconds slept before re-probing
    after each hang (on top of the ``timeout`` seconds the hang itself
    burned — ``(300, 600)`` with a 120 s timeout re-probes at ~t+7m and
    ~t+19m).
    """
    import subprocess
    import sys
    import time

    last = ""
    probes = 0
    hangs = 0
    attempt = 0
    while attempt <= retries:
        if attempt:
            delay = 5.0 * (2 ** (attempt - 1))
            # progress line: a probe cycle can take minutes; an operator
            # watching startup must see why the process appears frozen
            print(
                f"backend probe retry {attempt}/{retries} in "
                f"{delay:.0f}s: {last}",
                file=sys.stderr,
            )
            time.sleep(delay)
        attempt += 1
        probes += 1
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS") == "cpu":
            # this container's axon sitecustomize hangs a CPU-platform
            # process unless the pool IPs are cleared (the documented
            # env gotcha); when probing the real TPU the variable must
            # stay — it is how the tunnel is reached
            env["PALLAS_AXON_POOL_IPS"] = ""
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; "
                    "print(jax.default_backend(), len(jax.devices()))",
                ],
                capture_output=True,
                text=True,
                timeout=timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            last = f"backend init hung (> {timeout:.0f}s)"
            if hangs < len(hang_schedule):
                # the caller asked to wait out a tunnel outage: sleep the
                # long delay, then re-enter the probe loop from the top
                delay = float(hang_schedule[hangs])
                hangs += 1
                print(
                    f"backend init hung; long retry "
                    f"{hangs}/{len(hang_schedule)} in {delay:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(delay)
                attempt = 0
                continue
            # a hang (unlike a raised UNAVAILABLE) has never been observed
            # to clear quickly; don't burn the remaining short retries
            break
        if proc.returncode == 0:
            try:
                return int(proc.stdout.split()[-1]), ""
            except (ValueError, IndexError):
                return 1, ""  # healthy but unparsable: count conservatively
        tail = (proc.stderr or "").strip().splitlines()
        last = tail[-1][:200] if tail else f"probe rc={proc.returncode}"
    return 0, f"{last} after {probes} probe(s)"


def ensure_usable_backend(
    timeout: float = 120.0,
    retries: int = 2,
    hang_schedule: tuple = (),
) -> str:
    """Guarantee the first in-process jax call cannot hang: probe the
    default backend and force the CPU backend if it is unusable.

    Returns "" when the default backend is healthy, else a human-readable
    reason for the CPU fallback (callers log it). This is the degraded
    mode a control plane wants during an accelerator outage: decisions
    keep flowing on CPU instead of the process freezing at first jit.
    """
    count, reason = probe_default_backend(timeout, retries, hang_schedule)
    if count:
        return ""
    import jax

    jax.config.update("jax_platforms", "cpu")
    return f"default backend unavailable ({reason}); cpu fallback"


def configure_compile_cache(path: str) -> bool:
    """Enable JAX's persistent compilation cache at `path` (no-op when
    empty). Must run before the FIRST compile (not the backend init):
    every cache-missed compile taking >=1s is persisted, which covers
    the solver programs while skipping trivial host jits. A restarted
    process then reloads compiled programs instead of paying the 20-40s
    TPU compile again. Shared by the sidecar (--compile-cache-dir) and
    the standalone entry point (KARPENTER_COMPILE_CACHE)."""
    if not path:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # the cache SINGLETON latches its directory at the first compile: a
    # process that already compiled anything (with the cache implicitly
    # initialized as disabled) would silently ignore the new dir. Reset
    # so the next compile re-initializes against `path`. The reset API
    # is jax-internal; if a future jax drops it, the config above still
    # covers the not-yet-initialized case.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass
    return True
