"""Backend selection helpers for non-driver processes.

This container's sitecustomize (axon) imports jax at interpreter startup
with JAX_PLATFORMS=axon, and initializing the axon TPU client from a
non-driver process can hang or raise UNAVAILABLE. Tests and the multi-chip
dryrun therefore run on a virtual multi-device CPU backend. The sequence is
subtle enough that it lives here once, shared by tests/conftest.py and
__graft_entry__.dryrun_multichip:

- mutating os.environ["JAX_PLATFORMS"] is too late (jax already imported);
  platform selection must go through jax.config;
- XLA_FLAGS *is* read lazily at CPU client creation, so the env var works
  for the device count — but only if set before the first backend init.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu(n_devices: int) -> None:
    """Make the CPU backend the jax default with n_devices virtual devices.

    Must run before the first backend init (do NOT call jax.devices() or
    run any computation first — on this container that triggers the hanging
    axon init). Safe to call repeatedly; an existing device-count flag with
    a smaller count is replaced so a later caller asking for more devices
    is not silently truncated (which would fail mesh construction).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if match is None:
        flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
    elif int(match.group(1)) < n_devices:
        flags = (
            flags[: match.start()]
            + f"{_COUNT_FLAG}={n_devices}"
            + flags[match.end() :]
        )
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses we spawn

    import jax

    jax.config.update("jax_platforms", "cpu")
