"""Cron next-match engine for ScheduledCapacity patterns.

The reference converts its strongly-typed Pattern into a 5-field crontab and
asks robfig/cron for the next activation (reference:
pkg/metrics/producers/scheduledcapacity/crontabs.go:33-73). This is a
self-contained equivalent: 5 fields (minute hour day-of-month month
day-of-week), comma-separated value lists, month/weekday names, and the
standard cron rule that when BOTH day fields are restricted a day matches if
EITHER matches. next_after() returns the first matching wall-clock minute
strictly after the given time, in the given timezone.
"""

from __future__ import annotations

from datetime import datetime, timedelta
from typing import Optional, Set

_MONTH_ABBREVS = {
    "jan": 1, "feb": 2, "mar": 3, "apr": 4, "may": 5, "jun": 6,
    "jul": 7, "aug": 8, "sep": 9, "oct": 10, "nov": 11, "dec": 12,
}
_MONTH_NAMES = {
    "january": 1, "february": 2, "march": 3, "april": 4, "may": 5, "june": 6,
    "july": 7, "august": 8, "september": 9, "october": 10, "november": 11,
    "december": 12,
}
_MONTHS = {**_MONTH_ABBREVS, **_MONTH_NAMES}
_WEEKDAY_ABBREVS = {
    "sun": 0, "mon": 1, "tue": 2, "wed": 3, "thu": 4, "fri": 5, "sat": 6,
}
_WEEKDAY_NAMES = {
    "sunday": 0, "monday": 1, "tuesday": 2, "wednesday": 3, "thursday": 4,
    "friday": 5, "saturday": 6,
}
_WEEKDAYS = {**_WEEKDAY_ABBREVS, **_WEEKDAY_NAMES}

_FIELD_RANGES = {
    "minute": (0, 59),
    "hour": (0, 23),
    "dom": (1, 31),
    "month": (1, 12),
    "dow": (0, 7),  # 7 is accepted as Sunday
}


class CronParseError(ValueError):
    pass


def _parse_element(elem: str, field: str) -> int:
    elem = elem.strip().lower()
    if elem.isdigit():
        value = int(elem)
    elif field == "month" and elem in _MONTHS:
        value = _MONTHS[elem]
    elif field == "dow" and elem in _WEEKDAYS:
        value = _WEEKDAYS[elem]
    else:
        raise CronParseError(f"unable to parse {field} element {elem!r}")
    lo, hi = _FIELD_RANGES[field]
    if not lo <= value <= hi:
        raise CronParseError(f"{field} element {elem!r} out of range [{lo},{hi}]")
    if field == "dow" and value == 7:
        value = 0
    return value


def _parse_field(spec: Optional[str], field: str) -> Optional[Set[int]]:
    """None return means the field is a wildcard (unrestricted)."""
    if spec is None or spec.strip() == "*":
        return None
    return {_parse_element(e, field) for e in spec.split(",")}


class Cron:
    """A parsed 5-field cron schedule."""

    def __init__(
        self,
        minutes: Optional[str] = None,
        hours: Optional[str] = None,
        days: Optional[str] = None,
        months: Optional[str] = None,
        weekdays: Optional[str] = None,
    ):
        # Pattern semantics (reference: crontabs.go:44-49 and
        # metricsproducer.go Pattern docs): omitted minutes/hours mean 0,
        # omitted days/months/weekdays mean wildcard.
        self.minutes = _parse_field(minutes if minutes is not None else "0", "minute")
        self.hours = _parse_field(hours if hours is not None else "0", "hour")
        self.dom = _parse_field(days, "dom")
        self.months = _parse_field(months, "month")
        self.dow = _parse_field(weekdays, "dow")
        if self.minutes is None:
            self.minutes = set(range(0, 60))
        if self.hours is None:
            self.hours = set(range(0, 24))

    def _day_matches(self, t: datetime) -> bool:
        dow = (t.weekday() + 1) % 7  # cron numbering: Sunday=0
        if self.dom is not None and self.dow is not None:
            return t.day in self.dom or dow in self.dow
        if self.dom is not None:
            return t.day in self.dom
        if self.dow is not None:
            return dow in self.dow
        return True

    def next_after(self, t: datetime) -> datetime:
        """First matching minute strictly after t (same tzinfo as t)."""
        cur = t.replace(second=0, microsecond=0) + timedelta(minutes=1)
        # Bound the search at ~5 years of days, beyond which the schedule is
        # unsatisfiable (e.g. Feb 30).
        for _ in range(366 * 5 + 2):
            if self.months is not None and cur.month not in self.months:
                # advance to the first minute of the next month
                if cur.month == 12:
                    cur = cur.replace(
                        year=cur.year + 1, month=1, day=1, hour=0, minute=0
                    )
                else:
                    cur = cur.replace(month=cur.month + 1, day=1, hour=0, minute=0)
                continue
            if not self._day_matches(cur):
                cur = (cur + timedelta(days=1)).replace(hour=0, minute=0)
                continue
            # within a matching day, scan hour/minute sets directly
            found = self._next_in_day(cur)
            if found is not None:
                return found
            cur = (cur + timedelta(days=1)).replace(hour=0, minute=0)
        raise CronParseError("schedule has no matching time in the next 5 years")

    def _next_in_day(self, t: datetime) -> Optional[datetime]:
        for hour in sorted(self.hours):
            if hour < t.hour:
                continue
            for minute in sorted(self.minutes):
                if hour == t.hour and minute < t.minute:
                    continue
                return t.replace(hour=hour, minute=minute)
        return None
