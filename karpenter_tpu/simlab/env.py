"""SimEnv / BatchedSimEnv: the gym-style core of SimLab.

A simulated cluster is columnar state — per-HA-row replica counts —
driven by precomputed SEEDED TRAILS (demand, a forecast preview, a
price-multiplier schedule, and a fault schedule drawn from the chaos
registry). Precomputing the trails at reset() is what keeps the step a
PURE array program (ops/simstep.py): deterministic under the seed,
bit-identical between the device path and the numpy mirror, and
trivially batchable — `BatchedSimEnv` stacks N independently-seeded
clusters and advances them as ONE dispatch through the SolverService
seam (coalescing + health FSM + tracing for free, the standing
constraint every device-touching subsystem honors).

The gym contract (docs/simulator.md):

  obs                  = reset(seed)        # columnar fleet state
  obs, r, done, info   = step(action)       # action: f32[R] targets

The reward composes the three objectives the control plane itself is
judged on: SLO-violation ticks (demand outran capacity), hourly cost
(priced replica-ticks), and reconcile lead time (|target - actual|
backlog, the BLITZSCALE metric) — summed on host in float64 so every
path reduces in one order (the ops/simstep.py parity contract).

Never-block: `step(action)` sanitizes the action (None / wrong shape /
non-finite → the reactive target) and `run(policy)` catches policy
exceptions the same way — a broken policy degrades to reactive ticks,
mirroring the live `simlab` algorithm's contract, and the fallback is
counted in info, never raised.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

DEFAULT_TICKS = 64
DEFAULT_ROWS = 8

_F32 = np.float32


@dataclass(frozen=True)
class SimParams:
    """The simulated control-plane physics + reward weights shared by a
    scenario's clusters (docs/simulator.md)."""

    cap: float = 4.0  # demand served per replica
    hourly: float = 1.0  # on-demand price per replica-tick
    step_limit: float = 2.0  # max replica movement per tick (lead time)
    min_replicas: float = 0.0
    max_replicas: float = 64.0
    w_slo: float = 10.0  # reward weight: SLO-violation ticks
    w_cost: float = 0.05  # reward weight: priced replica-ticks
    w_lead: float = 0.2  # reward weight: reconcile backlog


@dataclass
class SimTrails:
    """One cluster's precomputed seeded episode (module docstring)."""

    demand: np.ndarray  # f32[T, R]
    forecast: np.ndarray  # f32[T, R] preview of the NEXT tick's demand
    price: np.ndarray  # f32[T] price multiplier (spot spike > 1)
    fault: np.ndarray  # f32[T] 1.0 = actuation blocked (chaos registry)
    replicas0: np.ndarray  # f32[R] initial replicas

    @property
    def ticks(self) -> int:
        return int(self.demand.shape[0])

    @property
    def rows(self) -> int:
        return int(self.demand.shape[1])


def composite_reward(params: SimParams, violation, cost, backlog):
    """The composite reward over per-tick per-row components, reduced
    on HOST in float64 (never in-kernel — the parity contract). Arrays
    with a leading cluster axis come back as per-cluster f64 rewards."""
    violation = np.asarray(violation, np.float64)
    cost = np.asarray(cost, np.float64)
    backlog = np.asarray(backlog, np.float64)
    # reduce the trailing [T, R] (or the whole [R] of a single tick);
    # leading cluster axes survive as per-cluster rewards
    axes = tuple(range(max(violation.ndim - 2, 0), violation.ndim))
    total = (
        params.w_slo * violation.sum(axis=axes)
        + params.w_cost * cost.sum(axis=axes)
        + params.w_lead * backlog.sum(axis=axes)
    )
    return -total


def _default_service():
    """A private SolverService for standalone envs (the simulate.py
    replay idiom): own gauge registry so a notebook env never pollutes
    the process /metrics surface."""
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver.service import SolverService

    return SolverService(registry=GaugeRegistry())


class SimEnv:
    """One simulated cluster with the gym contract (module docstring).

    `trails_fn(seed) -> SimTrails` regenerates the episode on every
    reset, so `reset(seed)` replays deterministically and distinct
    seeds draw distinct episodes from the same scenario."""

    def __init__(
        self,
        trails_fn: Callable[[int], SimTrails],
        params: Optional[SimParams] = None,
        seed: int = 0,
        service=None,
        backend: Optional[str] = None,
    ):
        self.params = params if params is not None else SimParams()
        self._trails_fn = trails_fn
        self._seed = int(seed)
        self._service = service if service is not None else _default_service()
        self._backend = backend
        self.trails: Optional[SimTrails] = None
        self.reset()

    # -- gym surface -------------------------------------------------------

    def reset(self, seed: Optional[int] = None) -> dict:
        if seed is not None:
            self._seed = int(seed)
        self.trails = self._trails_fn(self._seed)
        self._t = 0
        self._replicas = np.asarray(
            self.trails.replicas0, _F32
        ).copy()
        self._d_prev = np.zeros(self.trails.rows, _F32)
        self._f_prev = np.zeros(self.trails.rows, _F32)
        self._p_prev = _F32(1.0)
        return self._obs()

    def step(self, action=None):
        """Advance one tick; `action` is f32[R] replica targets (None or
        an unusable action falls back to the reactive target)."""
        from karpenter_tpu.ops import simstep as SK

        if self.done:
            raise RuntimeError("episode is done; call reset()")
        t = self._t
        trails = self.trails
        target, fell_back = self._sanitize(action)
        out = self._service.sim_step(
            SK.SimStepInputs(
                replicas=self._replicas,
                target=target,
                demand=trails.demand[t],
                price=np.asarray(trails.price[t]),
                fault=np.asarray(trails.fault[t]),
                **self._scalars(),
            ),
            backend=self._backend,
        )
        reward = float(
            composite_reward(
                self.params, out.violation, out.cost, out.backlog
            )
        )
        self._replicas = np.asarray(out.replicas, _F32)
        self._d_prev = np.asarray(trails.demand[t], _F32)
        self._f_prev = np.asarray(trails.forecast[t], _F32)
        self._p_prev = _F32(trails.price[t])
        self._t = t + 1
        info = {
            "violation_rows": float(np.asarray(out.violation).sum()),
            "hourly_cost": float(np.asarray(out.cost).sum()),
            "backlog": float(np.asarray(out.backlog).sum()),
            "fault": float(trails.fault[t]),
            "reactive_fallback": fell_back,
        }
        return self._obs(), reward, self.done, info

    @property
    def done(self) -> bool:
        return self._t >= self.trails.ticks

    def _obs(self) -> dict:
        """Columnar fleet state as the policy sees it: the LAST OBSERVED
        demand/forecast/price (zeros / 1.0 before the first tick — the
        same warm-up the in-kernel rollout policy sees)."""
        return {
            "tick": self._t,
            "rows": self.trails.rows,
            "replicas": self._replicas.copy(),
            "demand": self._d_prev.copy(),
            "forecast": self._f_prev.copy(),
            "price": float(self._p_prev),
        }

    # -- never-block helpers ----------------------------------------------

    def reactive_target(self) -> np.ndarray:
        """The reactive fallback action: chase last observed demand —
        the same f32 math as the in-kernel policy at knobs (0,0,0)."""
        raw = np.ceil(self._d_prev / _F32(self.params.cap))
        return np.clip(
            raw, _F32(self.params.min_replicas),
            _F32(self.params.max_replicas),
        ).astype(_F32)

    def _sanitize(self, action):
        if action is None:
            return self.reactive_target(), False
        arr = np.asarray(action, _F32)
        if arr.shape != self._replicas.shape or not np.all(
            np.isfinite(arr)
        ):
            return self.reactive_target(), True
        return arr, False

    def run(self, policy=None, reset: bool = True) -> dict:
        """Roll the episode out under `policy` (None = reactive) with
        the never-block contract: a raising policy degrades THAT TICK
        to the reactive target and the episode keeps stepping."""
        if reset:
            self.reset()
        if policy is not None and hasattr(policy, "reset"):
            policy.reset()
        total = 0.0
        violations = cost = backlog = 0.0
        policy_faults = fallbacks = 0
        obs = self._obs()
        while not self.done:
            action = None
            if policy is not None:
                try:
                    action = policy.act(obs)
                except Exception:  # noqa: BLE001 — never-block contract
                    policy_faults += 1
            obs, reward, _done, info = self.step(action)
            total += reward
            violations += info["violation_rows"]
            cost += info["hourly_cost"]
            backlog += info["backlog"]
            fallbacks += int(info["reactive_fallback"])
        return {
            "reward": total,
            "violation_ticks": violations,
            "hourly_cost": cost,
            "backlog": backlog,
            "policy_faults": policy_faults,
            "reactive_fallbacks": fallbacks,
            "final_replicas": self._replicas.copy(),
        }

    def _scalars(self) -> dict:
        p = self.params
        return {
            "cap": _F32(p.cap),
            "hourly": _F32(p.hourly),
            "step_limit": _F32(p.step_limit),
            "min_replicas": _F32(p.min_replicas),
            "max_replicas": _F32(p.max_replicas),
        }


class BatchedSimEnv:
    """N independently-seeded clusters stepped as ONE device program.

    Cluster i draws its episode from `trails_fn(seed + i)` (pass
    `share_trails=True` to evaluate N policies against ONE shared
    episode — the policy-search configuration). `step` advances all
    clusters per tick through SolverService.sim_step; `rollout(knobs)`
    runs whole episodes under the in-kernel tuned policy as a single
    vmapped dispatch (ops/simstep.py sim_rollout_vmapped)."""

    def __init__(
        self,
        trails_fn: Callable[[int], SimTrails],
        clusters: int,
        params: Optional[SimParams] = None,
        seed: int = 0,
        service=None,
        backend: Optional[str] = None,
        share_trails: bool = False,
    ):
        if clusters < 1:
            raise ValueError(f"clusters must be >= 1, got {clusters}")
        self.params = params if params is not None else SimParams()
        self.clusters = int(clusters)
        self._trails_fn = trails_fn
        self._seed = int(seed)
        self._share = share_trails
        self._service = service if service is not None else _default_service()
        self._backend = backend
        self.reset()

    def reset(self, seed: Optional[int] = None) -> dict:
        if seed is not None:
            self._seed = int(seed)
        if self._share:
            one = self._trails_fn(self._seed)
            per_cluster = [one] * self.clusters
        else:
            per_cluster = [
                self._trails_fn(self._seed + i)
                for i in range(self.clusters)
            ]
        self.trails = SimTrails(
            demand=np.stack([t.demand for t in per_cluster]),
            forecast=np.stack([t.forecast for t in per_cluster]),
            price=np.stack([t.price for t in per_cluster]),
            fault=np.stack([t.fault for t in per_cluster]),
            replicas0=np.stack([t.replicas0 for t in per_cluster]),
        )
        self._t = 0
        self._replicas = np.asarray(self.trails.replicas0, _F32).copy()
        self._d_prev = np.zeros_like(self._replicas)
        return self._obs()

    @property
    def ticks(self) -> int:
        return int(self.trails.demand.shape[1])

    @property
    def done(self) -> bool:
        return self._t >= self.ticks

    def _obs(self) -> dict:
        return {
            "tick": self._t,
            "replicas": self._replicas.copy(),
            "demand": self._d_prev.copy(),
        }

    def step(self, action=None):
        """One tick for ALL clusters: action f32[B, R] targets (None =
        reactive per cluster), one sim_step dispatch."""
        from karpenter_tpu.ops import simstep as SK

        if self.done:
            raise RuntimeError("episode is done; call reset()")
        t = self._t
        if action is None:
            raw = np.ceil(self._d_prev / _F32(self.params.cap))
            action = np.clip(
                raw, _F32(self.params.min_replicas),
                _F32(self.params.max_replicas),
            ).astype(_F32)
        out = self._service.sim_step(
            SK.SimStepInputs(
                replicas=self._replicas,
                target=np.asarray(action, _F32),
                demand=self.trails.demand[:, t],
                price=self.trails.price[:, t],
                fault=self.trails.fault[:, t],
                **_scalars(self.params),
            ),
            backend=self._backend,
        )
        rewards = composite_reward(
            self.params,
            np.asarray(out.violation)[:, None, :],
            np.asarray(out.cost)[:, None, :],
            np.asarray(out.backlog)[:, None, :],
        )
        self._replicas = np.asarray(out.replicas, _F32)
        self._d_prev = np.asarray(self.trails.demand[:, t], _F32)
        self._t = t + 1
        info = {
            "violation_rows": np.asarray(out.violation).sum(axis=-1),
            "hourly_cost": np.asarray(out.cost).sum(axis=-1),
            "backlog": np.asarray(out.backlog).sum(axis=-1),
        }
        return self._obs(), rewards, self.done, info

    def rollout(self, knobs) -> dict:
        """Whole episodes for all clusters under the in-kernel tuned
        policy, ONE vmapped dispatch. `knobs` is f32[3] (broadcast) or
        f32[B, 3] (per-cluster candidates — the search plane). Returns
        per-cluster composite rewards + component totals."""
        from karpenter_tpu.ops import simstep as SK

        knobs = np.asarray(knobs, _F32)
        if knobs.ndim == 1:
            knobs = np.broadcast_to(
                knobs, (self.clusters, knobs.shape[0])
            ).copy()
        out = self._service.sim_rollout(
            SK.SimRolloutInputs(
                replicas0=np.asarray(self.trails.replicas0, _F32),
                streak0=np.zeros_like(
                    np.asarray(self.trails.replicas0, _F32)
                ),
                demand=self.trails.demand,
                forecast=self.trails.forecast,
                price=self.trails.price,
                fault=self.trails.fault,
                knobs=knobs,
                **_scalars(self.params),
            ),
            backend=self._backend,
        )
        rewards = composite_reward(
            self.params, out.violation, out.cost, out.backlog
        )
        return {
            "rewards": rewards,
            "violation_ticks": np.asarray(
                out.violation, np.float64
            ).sum(axis=(1, 2)),
            "hourly_cost": np.asarray(out.cost, np.float64).sum(
                axis=(1, 2)
            ),
            "backlog": np.asarray(out.backlog, np.float64).sum(
                axis=(1, 2)
            ),
            "final_replicas": np.asarray(out.replicas, _F32),
            "outputs": out,
        }


def _scalars(p: SimParams) -> dict:
    return {
        "cap": _F32(p.cap),
        "hourly": _F32(p.hourly),
        "step_limit": _F32(p.step_limit),
        "min_replicas": _F32(p.min_replicas),
        "max_replicas": _F32(p.max_replicas),
    }
