"""Provenance ledger -> labeled training/eval stream (docs/simulator.md).

The decision-provenance ledger (observability/provenance.py) already
records, for every committed HA decision, exactly the supervision a
policy learner wants: the observed metric values and replica counts as
FEATURES, and the `winning_stage` — which pipeline stage best explains
the final desired count — plus the final count itself as LABELS.
`label_stream` reads that ring through the public `query()` surface
and reshapes it into flat numeric rows, so policy search / offline
eval consumes the SAME records operators debug with, with no second
bookkeeping path to drift.

Row shape (all floats; None-able ledger columns become NaN so numpy
consumers can mask):

  features  prev_replicas, base_desired, forecast_value,
            forecast_skill, cost_hourly, cost_risk, observed metric
            values (first OBSERVED_WIDTH, NaN-padded)
  labels    final_desired, stage (index into provenance.STAGES via
            `stage_index`)
"""

from __future__ import annotations

import math
from typing import List, Optional

from karpenter_tpu.observability.provenance import (
    OBSERVED_WIDTH,
    STAGES,
    default_ledger,
)

FEATURE_NAMES = (
    "prev_replicas",
    "base_desired",
    "forecast_value",
    "forecast_skill",
    "cost_hourly",
    "cost_risk",
) + tuple(f"observed_{i}" for i in range(OBSERVED_WIDTH))


def stage_index(stage: Optional[str]) -> int:
    """The stable label index of a winning stage (precedence order of
    provenance.STAGES); unknown/empty stages map to -1 so a consumer
    can drop or bucket them explicitly."""
    try:
        return STAGES.index(stage)
    except ValueError:
        return -1


def _float(value) -> float:
    if value is None:
        return math.nan
    return float(value)


def label_stream(
    ledger=None,
    kind: Optional[str] = None,
    tenant: Optional[str] = None,
    limit: Optional[int] = None,
) -> List[dict]:
    """Labeled rows from the ledger (the process default when `ledger`
    is None), oldest-first. Each row carries `features` (ordered by
    FEATURE_NAMES), `label_desired`, `label_stage` (index), plus the
    identity columns (`kind`/`tenant`/`name`/`group`/`stage`) for
    slicing an eval set."""
    ledger = ledger if ledger is not None else default_ledger()
    rows = []
    for record in ledger.query(kind=kind, tenant=tenant, limit=limit):
        observed = list(record.get("observed") or [])
        observed += [math.nan] * (OBSERVED_WIDTH - len(observed))
        features = [
            _float(record.get("prev_replicas")),
            _float(record.get("base_desired")),
            _float(record.get("forecast_value")),
            _float(record.get("forecast_skill")),
            _float(record.get("cost_hourly")),
            _float(record.get("cost_risk")),
        ] + observed[:OBSERVED_WIDTH]
        rows.append({
            "features": features,
            "label_desired": _float(record.get("final_desired")),
            "label_stage": stage_index(record.get("winning_stage")),
            "stage": record.get("winning_stage") or "",
            "kind": record.get("kind") or "",
            "tenant": record.get("tenant") or "",
            "name": record.get("name") or "",
            "group": record.get("group") or "",
        })
    return rows
