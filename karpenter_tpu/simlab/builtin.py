"""The built-in SimLab scenarios (docs/simulator.md).

Every pre-existing `--simulate` world re-registers here: the `run`
callables are the former `__main__._run_simulation` branch bodies
moved verbatim (same simulate.py calls, same argument spellings, same
provenance save/restore and trace-export handoff), so the pinned
deterministic digests are preserved bit-identically. `--sim-seed`
threads through every seeded world via `_resolved_seed` — the default
resolves to the seed each world always hardcoded, so default-seed
digests don't move.

Each scenario also carries a `trails(seed)` generator for the gym
plane: a themed seeded episode (demand trace, next-tick forecast
preview, price-multiplier schedule, fault schedule drawn from the
chaos registry) that `SimEnv`/`BatchedSimEnv` step through the device
seam. Trails keep a fault-free constant-demand tail of ticks//4 so
every episode has a reachable fixed point after faults clear — the
recovery property the seeded fuzz test pins (step_limit 2.0 traverses
32 replicas across a 16-tick tail).
"""

from __future__ import annotations

import json
import sys

import numpy as np

from karpenter_tpu.simlab.env import SimParams, SimTrails
from karpenter_tpu.simlab.registry import Scenario, register_scenario

_F32 = np.float32

TRAIL_TICKS = 64
TRAIL_ROWS = 8
FAULT_POINT = "simlab.actuate"


def _resolved_seed(args, default: int) -> int:
    """--sim-seed when given, else the seed the world always hardcoded
    (so default digests are byte-identical to the pre-registry CLI)."""
    seed = getattr(args, "sim_seed", None)
    return int(default) if seed is None else int(seed)


# -- trail generators ------------------------------------------------------


def _fault_trail(seed: int, ticks: int, probability: float, tail: int):
    """A fault schedule drawn honestly from the chaos registry: one
    seeded error plan evaluated per tick (the registry's plan-local RNG
    stream makes the trail a pure function of the seed), with the last
    `tail` ticks left clear so the episode can recover."""
    from karpenter_tpu.faults.registry import FaultRegistry

    trail = np.zeros(ticks, _F32)
    if probability <= 0.0:
        return trail
    registry = FaultRegistry(seed=seed)
    registry.plan(
        FAULT_POINT, mode="error", probability=probability, times=ticks
    )
    for t in range(ticks - tail):
        try:
            registry.fire(FAULT_POINT)
        except Exception:  # noqa: BLE001 — FaultInjected IS the signal
            trail[t] = 1.0
    return trail


def make_trails(  # lint: allow-complexity — one guard per trail theme knob
    seed: int,
    *,
    ticks: int = TRAIL_TICKS,
    rows: int = TRAIL_ROWS,
    base: float = 8.0,
    amplitude: float = 24.0,
    diurnal: bool = False,
    spike: float = 0.0,
    price_spike: float = 0.0,
    fault_probability: float = 0.0,
    params: SimParams = None,
) -> SimTrails:
    """One themed seeded episode (module docstring). All shaping runs
    in float64 and is cast to f32 once at the end, so the trails —
    like the kernels they feed — are a pure function of the seed."""
    p = params if params is not None else SimParams()
    rng = np.random.default_rng(seed)
    tail = ticks // 4
    row_scale = 0.5 + rng.random(rows)
    demand = base + rng.random((ticks, rows)) * amplitude * row_scale
    if diurnal:
        wave = np.clip(
            np.sin(2.0 * np.pi * np.arange(ticks) / ticks), 0.0, None
        )
        demand = demand * (0.25 + wave[:, None])
    if spike > 0.0:
        # a seeded burst third of the way in: the restart-storm /
        # preempt shape — demand jumps faster than the rate limit
        start = ticks // 3
        width = max(2, ticks // 8)
        demand[start : start + width] += spike * row_scale
    # constant-demand fault-free tail: the fixed point the fuzz pins
    demand[ticks - tail :] = demand[ticks - tail - 1]
    demand = np.clip(demand, 0.0, 0.85 * p.max_replicas * p.cap)
    # the forecast previews the NEXT tick's demand with seeded noise —
    # skillful but imperfect, which is what makes the blend-floor knob
    # a real decision instead of an oracle
    forecast = np.empty_like(demand)
    forecast[:-1] = demand[1:] + rng.normal(0.0, 1.0, (ticks - 1, rows))
    forecast[-1] = demand[-1]
    forecast = np.clip(forecast, 0.0, None)
    price = np.ones(ticks)
    if price_spike > 0.0:
        # seeded spot-spike ticks (none in the tail): the cost-ladder
        # knob's signal
        hot = rng.integers(0, ticks - tail, size=max(2, ticks // 8))
        price[hot] = 1.0 + price_spike
    fault = _fault_trail(seed, ticks, fault_probability, tail)
    replicas0 = np.clip(
        np.ceil(demand[0] / p.cap), p.min_replicas, p.max_replicas
    )
    return SimTrails(
        demand=demand.astype(_F32),
        forecast=forecast.astype(_F32),
        price=price.astype(_F32),
        fault=fault.astype(_F32),
        replicas0=replicas0.astype(_F32),
    )


def _trails_theme(**kwargs):
    """Bind a theme's knobs into the `trails(seed)` shape the registry
    stores (late-bound so every reset regenerates from the seed)."""

    def trails(seed: int) -> SimTrails:
        return make_trails(seed, **kwargs)

    return trails


# -- CLI runners (moved verbatim from __main__._run_simulation) ------------


def _run_trace(args, store) -> int:
    # the traced end-to-end replay (docs/observability.md): a seeded
    # consolidating world driven tick by tick, exporting a trace in
    # which the coalesced solver dispatch links the candidate
    # request spans and the SNG actuation closes the e2e window
    from karpenter_tpu.simulate import simulate_trace

    if args.provenance:
        # the replay's HA decides record into the ledger, and the
        # decisions JSONL lands next to the trace (the
        # --trace-export help's contract); the process default is
        # restored afterwards — an enabled default leaking out
        # would turn on provenance for a co-resident runtime that
        # never opted in (the simulate replays take the same care)
        from karpenter_tpu.observability import (
            default_ledger,
            reset_default_ledger,
            set_default_ledger,
        )

        saved_ledger = default_ledger()
        ledger = reset_default_ledger(enabled=True)
    try:
        report = simulate_trace(export_path=args.trace_export)
        if args.provenance:
            from karpenter_tpu.observability.provenance import (
                export_next_to_trace,
            )

            path, count = export_next_to_trace(ledger, args.trace_export)
            report["decisions_export"] = path
            report["decision_records"] = count
    finally:
        if args.provenance:
            set_default_ledger(saved_ledger)
    # simulate_trace already exported (the report pins the event
    # count): clear the flag so main's exit-time _export_trace
    # doesn't rewrite the identical file (or the decisions sibling)
    args.trace_export = None
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_constraints(args, store) -> int:
    # self-contained replay (own store, fake provider, scripted
    # clock): the constraint plane through a seeded zonal outage
    # (docs/constraints.md)
    from karpenter_tpu.simulate import simulate_constraints

    report = simulate_constraints(seed=_resolved_seed(args, 7))
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_eventloop(args, store) -> int:
    # self-contained replay (own stores, fake provider, scripted
    # clock): the same seeded pod-arrival trace tick-paced vs
    # event-driven (docs/solver-service.md "Event-driven reconcile")
    from karpenter_tpu.simulate import simulate_eventloop

    report = simulate_eventloop(
        arrivals=args.eventloop_arrivals,
        storm_events=args.eventloop_storm,
        debounce_s=args.event_debounce,
        seed=_resolved_seed(args, 0),
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_multitenant(args, store) -> int:
    # self-contained replay (no store, no provider): N seeded
    # tenant clusters stepped in lockstep through one
    # MultiTenantScheduler (docs/multitenancy.md); combines with
    # --cost implicitly (every lockstep tick runs decide + cost),
    # with --provenance (per-decision "why" records + ledger
    # JSONL), and with --trace-export
    from karpenter_tpu.simulate import simulate_multitenant

    report = simulate_multitenant(
        tenants=args.tenants,
        seed=_resolved_seed(args, 0),
        tenant_config=args.tenant_config,
        provenance=args.provenance,
        trace_export=args.trace_export,
    )
    # simulate_multitenant exported trace + decisions itself
    args.trace_export = None
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_poolgroups(args, store) -> int:
    # self-contained replay (own stores, fake provider): a decode-heavy
    # traffic-mix storm through a prefill/decode PoolGroup, coordinated
    # (--poolgroups joint allocator) vs uncoordinated per-pool loops
    # (docs/poolgroups.md)
    from karpenter_tpu.simulate import simulate_poolgroups

    report = simulate_poolgroups(seed=_resolved_seed(args, 0))
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_cost(args, store) -> int:
    # self-contained replay (own stores, lagged fake provider):
    # warm pool on vs off through the cost-aware pipeline
    from karpenter_tpu.simulate import simulate_cost

    report = simulate_cost(
        horizon_s=args.forecast_horizon,
        default_hourly=args.cost_default_hourly,
        spot_multiplier=args.cost_spot_multiplier,
        provenance=args.provenance,
        seed=_resolved_seed(args, 0),
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_forecast(args, store) -> int:
    # self-contained replay (no store, no provider): proactive vs
    # reactive on a scripted diurnal ramp
    from karpenter_tpu.simulate import simulate_forecast

    report = simulate_forecast(
        horizon_s=args.forecast_horizon,
        model=args.forecast_model,
        seed=_resolved_seed(args, 0),
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_restart_storm(args, store) -> int:
    # self-contained replay (own store/provider/journal dir): a
    # seeded kill-and-restart storm pinning the crash-safety
    # contract — exactly-once actuation, FSM resumption, fencing
    from karpenter_tpu.simulate import simulate_restart_storm

    report = simulate_restart_storm(
        crashes=args.storm_crashes,
        seed=_resolved_seed(args, 0),
        journal_dir=args.journal_dir,
        warmup_ticks=args.recovery_warmup_ticks,
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_failover(args, store) -> int:
    # self-contained replay (own store/planes/journal root): a seeded
    # leader-kill failover pinning the replicated-control-plane
    # contract — fenced handoff, exactly-once actuation across the
    # handoff, reconvergence, stale-write rejection
    from karpenter_tpu.simulate import simulate_failover

    report = simulate_failover(
        replicas=args.replicas,
        seed=_resolved_seed(args, 0),
        journal_dir=args.journal_dir,
        warmup_ticks=args.recovery_warmup_ticks,
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_preempt(args, store) -> int:
    # self-contained replay (no live store, no provider): a seeded
    # spot-reclaim storm over mixed on-demand/spot pools
    from karpenter_tpu.simulate import simulate_preempt

    report = simulate_preempt(
        preempt_budget=args.preempt_budget,
        default_priority=args.default_priority,
        seed=_resolved_seed(args, 0),
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _run_karpenter(args, store) -> int:
    """The default dry-run world over the live/WAL store — consolidate,
    what-if delta, or the plain solve — moved verbatim (one runner for
    all three so the consolidate-over-what-if precedence and the
    what-if file validation keep their exact pre-registry order)."""
    from karpenter_tpu.runtime import KarpenterRuntime, Options
    from karpenter_tpu.simulate import simulate, simulate_delta

    what_if = None
    if args.what_if:
        from karpenter_tpu.utils.configfile import load_json_or_yaml

        what_if = load_json_or_yaml(args.what_if)
        if not isinstance(what_if, list):
            print(
                f"--what-if {args.what_if}: expected a LIST of group specs",
                file=sys.stderr,
            )
            return 2

    # a runtime only to materialize the store the flags describe (WAL dir
    # or live apiserver) and the optional solver sidecar; no controllers
    # tick, nothing is mutated
    runtime = KarpenterRuntime(
        Options(
            data_dir=args.data_dir,
            solver_uri=args.solver_uri,
            cloud_provider=args.cloud_provider,
            verbose=args.verbose,
            cost_default_hourly=args.cost_default_hourly,
            cost_spot_multiplier=args.cost_spot_multiplier,
            pricing_file=args.pricing_file,
            sim_seed=getattr(args, "sim_seed", None),
        ),
        store=store,
    )
    # route through the runtime's shared solve service (not the raw
    # sidecar client): the dry run gets the same queueing, deadlines,
    # and numpy fallback the production tick gets
    solver = runtime.solver_service.solve
    # the scale-from-zero seam the production solve uses: without it,
    # empty groups with a nodeGroupRef would simulate as infeasible
    resolver = runtime.producer_factory.template_resolver()
    try:
        if args.consolidate:
            from karpenter_tpu.simulate import simulate_consolidation

            report = simulate_consolidation(
                runtime.store, service=runtime.solver_service
            )
        elif what_if is not None:
            report = simulate_delta(
                runtime.store, what_if, solver=solver,
                template_resolver=resolver,
                cost_model=runtime.cost_model,
            )
        else:
            report = simulate(
                runtime.store, solver=solver, template_resolver=resolver,
                cost_model=runtime.cost_model,
            )
        print(json.dumps(report, indent=2, sort_keys=True))
    finally:
        runtime.close()
    return 0


# -- registrations ---------------------------------------------------------
# Ascending `order` preserves the old elif chain's precedence exactly;
# the trace world's not-any-other-flag predicate is the same guard the
# chain's first branch carried.


def _select_trace(args) -> bool:
    return bool(args.trace_export) and not (
        args.forecast or args.restart_storm or args.failover
        or args.preempt or args.consolidate or args.what_if
        or args.cost or args.multitenant or args.eventloop
    )


register_scenario(Scenario(
    name="trace",
    description="traced end-to-end consolidating replay exporting "
    "Chrome-trace JSONL",
    flags="--trace-export FILE",
    order=10,
    select=_select_trace,
    run=_run_trace,
    seeded=False,
    trails=_trails_theme(fault_probability=0.05),
))

register_scenario(Scenario(
    name="constraints",
    description="constraint plane through a seeded zonal outage "
    "(spread/affinity/dead-zone report)",
    flags="--constraints",
    order=20,
    select=lambda args: bool(args.constraints),
    run=_run_constraints,
    default_seed=7,
    trails=_trails_theme(spike=40.0, fault_probability=0.1),
))

register_scenario(Scenario(
    name="eventloop",
    description="seeded pod-arrival trace tick-paced vs event-driven "
    "(lead time + storm coalescing)",
    flags="--eventloop",
    order=30,
    select=lambda args: bool(args.eventloop),
    run=_run_eventloop,
    trails=_trails_theme(spike=60.0),
))

register_scenario(Scenario(
    name="multitenant",
    description="N seeded tenant clusters in lockstep through one "
    "scheduler (cross-tenant batched dispatches)",
    flags="--multitenant",
    order=40,
    select=lambda args: bool(args.multitenant),
    run=_run_multitenant,
    trails=_trails_theme(diurnal=True, amplitude=48.0),
))

register_scenario(Scenario(
    name="poolgroups",
    description="decode-heavy traffic-mix storm through a "
    "prefill/decode PoolGroup, joint vs per-pool loops",
    flags="--poolgroups",
    order=45,
    select=lambda args: bool(getattr(args, "poolgroups", False)),
    run=_run_poolgroups,
    trails=_trails_theme(
        diurnal=True, amplitude=64.0, spike=48.0,
        fault_probability=0.05,
    ),
))

register_scenario(Scenario(
    name="cost",
    description="warm pool on vs off through the cost-aware pipeline "
    "(spot spikes + clamps)",
    flags="--cost",
    order=50,
    select=lambda args: bool(args.cost),
    run=_run_cost,
    trails=_trails_theme(
        diurnal=True, amplitude=96.0, price_spike=1.5,
        fault_probability=0.05,
    ),
))

register_scenario(Scenario(
    name="forecast",
    description="proactive vs reactive autoscaling on a scripted "
    "diurnal ramp (provisioning lead)",
    flags="--forecast",
    order=60,
    select=lambda args: bool(args.forecast),
    run=_run_forecast,
    trails=_trails_theme(diurnal=True, amplitude=120.0, base=8.0),
))

register_scenario(Scenario(
    name="restart-storm",
    description="seeded kill-and-restart storm pinning exactly-once "
    "actuation + FSM resumption",
    flags="--restart-storm",
    order=70,
    select=lambda args: bool(args.restart_storm),
    run=_run_restart_storm,
    trails=_trails_theme(spike=50.0, fault_probability=0.25),
))

register_scenario(Scenario(
    name="failover",
    description="seeded leader-kill over replicated solver replicas "
    "(fenced handoff + reconvergence)",
    flags="--failover",
    order=72,
    select=lambda args: bool(args.failover),
    run=_run_failover,
    trails=_trails_theme(spike=50.0, fault_probability=0.3),
))

register_scenario(Scenario(
    name="preempt",
    description="seeded spot-reclaim storm over mixed on-demand/spot "
    "pools (preemption budgets)",
    flags="--preempt",
    order=80,
    select=lambda args: bool(args.preempt),
    run=_run_preempt,
    trails=_trails_theme(
        spike=70.0, price_spike=2.0, fault_probability=0.15
    ),
))

register_scenario(Scenario(
    name="consolidate",
    description="dry-run consolidation plan over the live/WAL store "
    "(drainability + repack)",
    flags="--consolidate",
    order=90,
    select=lambda args: bool(args.consolidate),
    run=_run_karpenter,
    seeded=False,
    trails=_trails_theme(amplitude=12.0, fault_probability=0.05),
))

register_scenario(Scenario(
    name="what-if",
    description="baseline vs what-if delta solve over hypothetical "
    "node groups",
    flags="--what-if FILE",
    order=95,
    select=lambda args: bool(args.what_if),
    run=_run_karpenter,
    seeded=False,
    trails=_trails_theme(amplitude=12.0),
))

register_scenario(Scenario(
    name="karpenter",
    description="default dry-run solve over the live/WAL store "
    "(pendingCapacity producers)",
    flags="(no extra flags)",
    order=100,
    select=lambda args: True,
    run=_run_karpenter,
    seeded=False,
    trails=_trails_theme(),
))
