"""The SimLab policy plane (docs/simulator.md "Policy search").

A `Policy` maps the gym observation (columnar fleet state) to per-HA
replica targets. Three implementations:

  ReactivePolicy     chase last observed demand — the same f32 math as
                     the in-kernel policy at knobs (0, 0, 0), so it is
                     the shared baseline for every comparison.
  SearchTunedPolicy  the in-kernel 3-knob decision surface
                     (ops/simstep.py `_policy_math`) evaluated on host
                     tick by tick — bit-identical to what the batched
                     rollout scored, so a searched knob vector behaves
                     in `SimEnv.step` exactly as it did in search.
  search_tuned_policy  the search itself: a deterministic knob grid
                     plus one perturbation-refinement round, every
                     candidate population evaluated against ONE shared
                     seeded episode as a single vmapped rollout
                     dispatch (`BatchedSimEnv(share_trails=True)`), the
                     reactive knobs always in the population so the
                     winner's margin over the baseline is part of the
                     result.

The frozen winner slots into the live runtime as the `simlab`
algorithm (autoscaler/algorithms/simlab_policy.py) behind the
never-block contract; `FROZEN_KNOBS` is the shipped default vector.
"""

from __future__ import annotations

from dataclasses import dataclass
from types import SimpleNamespace
from typing import Optional, Protocol

import numpy as np

from karpenter_tpu.ops.simstep import (
    KNOB_BLEND_FLOOR,
    KNOB_COST_WEIGHT,
    KNOB_STAB_WINDOW,
    KNOBS,
    _policy_math,
)
from karpenter_tpu.simlab.env import BatchedSimEnv, SimParams

_F32 = np.float32

KNOB_NAMES = ("blend_floor", "cost_weight", "stab_window")

# knobs (0,0,0) IS the reactive baseline (ops/simstep.py docstring)
REACTIVE_KNOBS = np.zeros(KNOBS, _F32)
# the shipped default for the live `simlab` algorithm: provision to the
# full forecast preview, shed half the spike-priced surplus, hold
# scale-downs for two ticks — the grid winner on the forecast scenario
FROZEN_KNOBS = np.asarray([1.0, 0.5, 2.0], _F32)

# the deterministic search grid (4 x 4 x 3 = 48 candidates + reactive)
GRID_BLEND_FLOOR = (0.0, 0.5, 1.0, 1.25)
GRID_COST_WEIGHT = (0.0, 0.25, 0.5, 1.0)
GRID_STAB_WINDOW = (0.0, 2.0, 4.0)
# perturbation deltas for the refinement round, per knob
_REFINE_DELTAS = ((-0.25, 0.0, 0.25), (-0.125, 0.0, 0.125), (-1.0, 0.0, 1.0))


class Policy(Protocol):
    """observe -> per-HA replica targets (f32[R]); `reset()` clears any
    episode-local state before a fresh rollout."""

    def act(self, obs: dict) -> np.ndarray: ...

    def reset(self) -> None: ...


class ReactivePolicy:
    """The baseline: ceil(last observed demand / cap), clipped."""

    def __init__(self, params: Optional[SimParams] = None):
        self.params = params if params is not None else SimParams()

    def reset(self) -> None:
        pass

    def act(self, obs: dict) -> np.ndarray:
        p = self.params
        raw = np.ceil(np.asarray(obs["demand"], _F32) / _F32(p.cap))
        return np.clip(
            raw, _F32(p.min_replicas), _F32(p.max_replicas)
        ).astype(_F32)


class SearchTunedPolicy:
    """The 3-knob tuned policy on host: each `act` runs the SAME f32
    `_policy_math` the batched search rollout ran in-kernel, carrying
    the scale-down streak as episode state — so the frozen winner's
    gym-loop behavior is bit-identical to its searched score."""

    def __init__(
        self, knobs=FROZEN_KNOBS, params: Optional[SimParams] = None
    ):
        self.knobs = np.asarray(knobs, _F32)
        if self.knobs.shape != (KNOBS,):
            raise ValueError(
                f"knobs must be f32[{KNOBS}], got {self.knobs.shape}"
            )
        self.params = params if params is not None else SimParams()
        self._streak: Optional[np.ndarray] = None

    def reset(self) -> None:
        self._streak = None

    def act(self, obs: dict) -> np.ndarray:
        p = self.params
        replicas = np.asarray(obs["replicas"], _F32)
        if self._streak is None or self._streak.shape != replicas.shape:
            self._streak = np.zeros_like(replicas)
        scalars = SimpleNamespace(
            cap=_F32(p.cap),
            min_replicas=_F32(p.min_replicas),
            max_replicas=_F32(p.max_replicas),
        )
        target, self._streak = _policy_math(
            np,
            self.knobs,
            np.asarray(obs["demand"], _F32),
            np.asarray(obs["forecast"], _F32),
            np.asarray(_F32(obs["price"])),
            replicas,
            self._streak,
            scalars,
        )
        return np.asarray(target, _F32)

    @property
    def blend_floor(self) -> float:
        return float(self.knobs[KNOB_BLEND_FLOOR])

    @property
    def cost_weight(self) -> float:
        return float(self.knobs[KNOB_COST_WEIGHT])

    @property
    def stab_window(self) -> float:
        return float(self.knobs[KNOB_STAB_WINDOW])


@dataclass
class SearchResult:
    """One search's outcome: the winning knob vector, its composite
    reward on the search episode, the reactive baseline's reward on the
    SAME episode, and how much work the search did."""

    knobs: np.ndarray  # f32[3] winner
    reward: float  # winner's composite reward (higher is better)
    baseline_reward: float  # reactive knobs on the same episode
    candidates: int  # total knob vectors evaluated
    dispatches: int  # vmapped rollout dispatches (one per round)
    rewards: dict  # {knob-tuple: reward} for every candidate

    @property
    def margin(self) -> float:
        return self.reward - self.baseline_reward

    def policy(self, params: Optional[SimParams] = None) -> SearchTunedPolicy:
        return SearchTunedPolicy(self.knobs, params=params)


def _grid_candidates() -> np.ndarray:
    rows = [
        (bf, cw, sw)
        for bf in GRID_BLEND_FLOOR
        for cw in GRID_COST_WEIGHT
        for sw in GRID_STAB_WINDOW
    ]
    return np.asarray(rows, _F32)


def _refine_candidates(winner: np.ndarray) -> np.ndarray:
    """Deterministic perturbation neighborhood around the grid winner
    (all knobs floored at 0 — negative floors/weights/windows have no
    meaning in the kernel)."""
    rows = [
        (
            winner[KNOB_BLEND_FLOOR] + d0,
            winner[KNOB_COST_WEIGHT] + d1,
            winner[KNOB_STAB_WINDOW] + d2,
        )
        for d0 in _REFINE_DELTAS[0]
        for d1 in _REFINE_DELTAS[1]
        for d2 in _REFINE_DELTAS[2]
    ]
    return np.clip(np.asarray(rows, _F32), 0.0, None)


def _evaluate(env: BatchedSimEnv, candidates: np.ndarray) -> np.ndarray:
    """Per-candidate composite rewards: the whole population rides ONE
    vmapped rollout dispatch (every cluster shares the episode, only
    the knob rows differ)."""
    return np.asarray(env.rollout(candidates)["rewards"], np.float64)


def search_tuned_policy(
    trails_fn,
    seed: int = 0,
    params: Optional[SimParams] = None,
    service=None,
    backend: Optional[str] = None,
    refine: bool = True,
) -> SearchResult:
    """Grid search + one perturbation-refinement round over the 3-knob
    surface against one shared seeded episode (module docstring).
    Deterministic end to end: the grid, the episode, and the refinement
    neighborhood are all pure functions of `seed`."""
    params = params if params is not None else SimParams()
    grid = np.concatenate([REACTIVE_KNOBS[None, :], _grid_candidates()])
    rewards: dict = {}
    dispatches = 0

    def run_round(candidates: np.ndarray) -> None:
        nonlocal dispatches
        env = BatchedSimEnv(
            trails_fn,
            clusters=len(candidates),
            params=params,
            seed=seed,
            service=service,
            backend=backend,
            share_trails=True,
        )
        scores = _evaluate(env, candidates)
        dispatches += 1
        for knobs, score in zip(candidates, scores):
            rewards[tuple(float(k) for k in knobs)] = float(score)

    run_round(grid)
    if refine:
        best = max(rewards, key=lambda k: rewards[k])
        run_round(_refine_candidates(np.asarray(best, _F32)))

    best = max(rewards, key=lambda k: rewards[k])
    baseline = rewards[tuple(float(k) for k in REACTIVE_KNOBS)]
    return SearchResult(
        knobs=np.asarray(best, _F32),
        reward=rewards[best],
        baseline_reward=baseline,
        candidates=len(rewards),
        dispatches=dispatches,
        rewards=rewards,
    )
