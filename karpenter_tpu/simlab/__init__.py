"""SimLab: trace-driven fleet simulator with a gym-style step API.

ROADMAP item 1 (docs/simulator.md): promote the deterministic
`--simulate` replay worlds into a seeded trace-driven simulator whose
batched device stepping makes policy search and scenario fuzzing run
thousands of cluster-days per minute — the same batch-everything trick
as the decide/cost/forecast kernels. Three planes:

  registry   `Scenario` specs (seeded workload trace generator, fault
             schedule drawn from the chaos registry, pricing events) —
             every existing `--simulate` world re-registers here with
             its CLI replay preserved bit-identically, and
             `--simulate --list` prints the catalog.
  env        `SimEnv` reset(seed)/step(action) over columnar fleet
             state; `BatchedSimEnv` stacks N independently-seeded
             clusters and advances them as ONE vmapped device program
             through the SolverService seam (ops/simstep.py).
  policy     a `Policy` protocol, the reactive baseline, and
             `SearchTunedPolicy` — grid/evolution search over decision
             knobs against batched rollouts; the frozen winner slots
             into the live runtime as the `simlab` algorithm
             (autoscaler/algorithms/simlab_policy.py) behind the
             never-block contract, with the provenance ledger exported
             as the labeled training/eval stream (simlab/labels.py).
"""

from karpenter_tpu.simlab.env import (
    BatchedSimEnv,
    SimEnv,
    SimParams,
    SimTrails,
    composite_reward,
)
from karpenter_tpu.simlab.labels import label_stream, stage_index
from karpenter_tpu.simlab.policy import (
    Policy,
    ReactivePolicy,
    SearchResult,
    SearchTunedPolicy,
    search_tuned_policy,
)
from karpenter_tpu.simlab.registry import (
    Scenario,
    catalog,
    catalog_text,
    get_scenario,
    register_scenario,
    scenarios,
    select_for,
)

# registering the built-in scenarios is an import side effect, like the
# algorithm registry's trend/simlab registrations
import karpenter_tpu.simlab.builtin  # noqa: F401,E402

__all__ = [
    "BatchedSimEnv",
    "Policy",
    "ReactivePolicy",
    "Scenario",
    "SearchResult",
    "SearchTunedPolicy",
    "SimEnv",
    "SimParams",
    "SimTrails",
    "catalog",
    "catalog_text",
    "composite_reward",
    "get_scenario",
    "label_stream",
    "register_scenario",
    "scenarios",
    "search_tuned_policy",
    "select_for",
    "stage_index",
]
