"""The SimLab scenario registry (docs/simulator.md).

A `Scenario` is the declarative record that replaces the hand-grown
`__main__._run_simulation` dispatch chain: every `--simulate` world
registers NAME + one-line DESCRIPTION + the CLI FLAGS that select it +
a `select` predicate + a `run(args, store)` callable that replays the
world bit-identically (the pinned digests are the contract), plus —
for worlds promoted to the gym plane — a `trails(seed)` generator the
`SimEnv`/`BatchedSimEnv` core steps through the device seam.

`--simulate --list` prints `catalog_text()`, and the doc-drift lint in
tests/test_simlab.py holds the docs/simulator.md catalog table and
this registry in two-direction sync (the PR 12 metrics-lint pattern).

Selection order: predicates are evaluated in ascending `order`, first
match wins — this preserves the precedence the old elif chain encoded
(trace-only before constraints before eventloop ... before the default
karpenter world).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from karpenter_tpu.simlab.env import SimParams, SimTrails


@dataclass(frozen=True)
class Scenario:
    """One registered simulation world (module docstring)."""

    name: str
    description: str  # one line, mirrored into docs/simulator.md
    flags: str  # the CLI spelling that selects it, for --list
    order: int  # selection precedence (ascending, first match wins)
    select: Callable[[object], bool]  # predicate over parsed args
    run: Callable[[object, object], None]  # (args, store) CLI replay
    seeded: bool = True  # honors --sim-seed
    default_seed: int = 0  # the hardcoded seed the digests pin
    trails: Optional[Callable[[int], SimTrails]] = None  # gym plane
    params: SimParams = field(default_factory=SimParams)


_REGISTRY: Dict[str, Scenario] = {}


def register_scenario(scenario: Scenario) -> Scenario:
    if scenario.name in _REGISTRY:
        raise ValueError(f"scenario {scenario.name!r} already registered")
    _REGISTRY[scenario.name] = scenario
    return scenario


def scenarios() -> Dict[str, Scenario]:
    """Registered scenarios in selection (ascending `order`) order."""
    return dict(
        sorted(_REGISTRY.items(), key=lambda kv: kv[1].order)
    )


def get_scenario(name: str) -> Scenario:
    try:
        return _REGISTRY[name]
    except KeyError:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(
            f"unknown scenario {name!r} (registered: {known})"
        ) from None


def select_for(args) -> Scenario:
    """The scenario whose predicate matches the parsed CLI args (first
    match in `order`; the default `karpenter` world matches always)."""
    for scenario in scenarios().values():
        if scenario.select(args):
            return scenario
    raise RuntimeError(
        "no scenario matched --simulate flags; the default world "
        "should be unconditional"
    )


def catalog() -> list:
    """Rows for --simulate --list and the docs drift lint: (name,
    description, flags, seeded)."""
    return [
        (s.name, s.description, s.flags, s.seeded)
        for s in scenarios().values()
    ]


def catalog_text() -> str:
    rows = catalog()
    name_w = max(len(r[0]) for r in rows)
    flags_w = max(len(r[2]) for r in rows)
    lines = ["Registered simulation scenarios (--simulate ...):", ""]
    for name, desc, flags, seeded in rows:
        seed_tag = "--sim-seed" if seeded else "fixed"
        lines.append(
            f"  {name:<{name_w}}  {flags:<{flags_w}}  "
            f"[{seed_tag}]  {desc}"
        )
    lines.append("")
    lines.append(
        "Seeded scenarios accept --sim-seed N; defaults reproduce the "
        "pinned digests."
    )
    return "\n".join(lines)
