from karpenter_tpu.store.store import (
    ConflictError,
    NotFoundError,
    Scale,
    Store,
    register_scale_kind,
)

__all__ = ["Store", "Scale", "NotFoundError", "ConflictError", "register_scale_kind"]
