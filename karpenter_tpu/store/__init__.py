from karpenter_tpu.store.store import (
    ConflictError,
    NotFoundError,
    Scale,
    Store,
    register_scale_kind,
)


def __getattr__(name):
    # lazy: persistence pulls in the serialization codec; keep plain Store
    # imports light
    if name in ("DurableStore", "open_store", "register_persistent_kind"):
        from karpenter_tpu.store import persistence

        return getattr(persistence, name)
    raise AttributeError(name)


__all__ = [
    "Store",
    "Scale",
    "NotFoundError",
    "ConflictError",
    "register_scale_kind",
    "DurableStore",
    "open_store",
    "register_persistent_kind",
]
