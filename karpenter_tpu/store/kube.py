"""Real-cluster mode: the store backed by a kube-apiserver.

The reference's coordination bus IS the apiserver (client-go informers +
writes; reference: SURVEY.md §2.2, pkg/controllers/manager.go). This module
gives the TPU build the same mode without any kubernetes client library:

- `KubeClient` — a minimal typed REST client over urllib (bearer token +
  CA, in-cluster defaults): list/watch streams, create/update/delete,
  merge-patch status, and the scale subresource.
- `KubeStore` — the Store facade the rest of the framework already
  programs against. Reads and watch callbacks ride an in-memory mirror
  kept current by apiserver watch streams (the informer pattern, which is
  also what makes PendingFeed/DurableStore-free operation correct here);
  writes go straight to the apiserver, whose echo updates the mirror.
  Write-then-read may briefly see the pre-write state — level-triggered
  reconciles recompute from scratch, so staleness only delays, never
  corrupts (the exact consistency model the reference runs under).

Lease operations (leader election) bypass the mirror: they are
read-modify-write against coordination.k8s.io directly, since a stale
lease read must lose the conflict, not win it.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import socket as _socket
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.api.serialization import (
    _rfc3339_to_epoch,
    from_manifest,
    to_dict,
)

_socket_timeout = _socket.timeout
from karpenter_tpu.leaderelection import Lease
from karpenter_tpu.store.store import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    NotFoundError,
    Scale,
    Store,
)
from karpenter_tpu.utils.log import logger

log = logger()

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (api prefix, plural, namespaced)
RESOURCES: Dict[str, Tuple[str, str, bool]] = {
    "HorizontalAutoscaler": (
        "apis/autoscaling.karpenter.sh/v1alpha1",
        "horizontalautoscalers",
        True,
    ),
    "MetricsProducer": (
        "apis/autoscaling.karpenter.sh/v1alpha1",
        "metricsproducers",
        True,
    ),
    "ScalableNodeGroup": (
        "apis/autoscaling.karpenter.sh/v1alpha1",
        "scalablenodegroups",
        True,
    ),
    "Pod": ("api/v1", "pods", True),
    "Node": ("api/v1", "nodes", False),
    # labels resolve namespaceSelector terms in inter-pod affinity
    "Namespace": ("api/v1", "namespaces", False),
}

WATCHED_KINDS = tuple(RESOURCES)

# negative-cache lifetime for discovery misses (resolve_kind): long
# enough that a misconfigured HA costs one discovery walk per window
# instead of one per reconcile, short enough that installing the
# missing CRD is picked up without a restart
DISCOVERY_MISS_TTL = 30.0

_LEASE_API = "apis/coordination.k8s.io/v1"


def _epoch_to_rfc3339(ts: float) -> str:
    return (
        _dt.datetime.fromtimestamp(ts, _dt.timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


def encode_for_write(obj) -> dict:
    """Manifest for POST/PUT: user-facing codec + the concurrency token."""
    doc = to_dict(obj)
    meta = doc.setdefault("metadata", {})
    if obj.metadata.resource_version:
        meta["resourceVersion"] = str(obj.metadata.resource_version)
    return doc


def decode_from_read(doc: dict):
    """Apiserver object -> API object (lenient: unknown fields dropped,
    RFC3339 timestamps to epoch)."""
    obj = from_manifest(doc, lenient=True)
    meta = doc.get("metadata", {})
    rv = meta.get("resourceVersion")
    if rv is not None:
        # resourceVersions are opaque strings per the k8s API conventions
        # (etcd's happen to be numeric, but nothing guarantees it); keep
        # non-numeric ones as strings — the mirror only needs equality.
        try:
            obj.metadata.resource_version = int(rv)
        except ValueError:
            obj.metadata.resource_version = rv
    uid = meta.get("uid")
    if uid:
        obj.metadata.uid = uid
    return obj


def _null_vanished(old: dict, new: dict) -> dict:
    """JSON merge-patch body that also DELETES keys present in `old` but
    absent from `new` (RFC 7386: null means remove). Recurses into maps so
    nested deletions (condition fields, per-resource entries) propagate."""
    out = dict(new)
    for key, old_value in old.items():
        if key not in new:
            out[key] = None
        elif isinstance(old_value, dict) and isinstance(new[key], dict):
            out[key] = _null_vanished(old_value, new[key])
    return out


def _make_ssl_context(base_url: str, insecure: bool, ca_file):
    """SSL context for an https apiserver URL (None for plain http):
    CERT_NONE when insecure, else the given CA / the in-cluster
    serviceaccount CA / system defaults."""
    if not base_url.startswith("https"):
        return None
    if insecure:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    ca = ca_file or (
        os.path.join(_SA_DIR, "ca.crt")
        if os.path.exists(os.path.join(_SA_DIR, "ca.crt"))
        else None
    )
    return ssl.create_default_context(cafile=ca)


class KubeClient:
    """Minimal apiserver REST client; no client library, just urllib."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout: float = 30.0,
    ):
        self.base_url = (
            base_url
            or os.environ.get("KUBERNETES_SERVICE_HOST")
            and (
                "https://"
                + os.environ["KUBERNETES_SERVICE_HOST"]
                + ":"
                + os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            )
            or "https://kubernetes.default.svc"
        ).rstrip("/")
        self._token = token
        self._token_file = token_file or (
            os.path.join(_SA_DIR, "token")
            if token is None and os.path.exists(os.path.join(_SA_DIR, "token"))
            else None
        )
        self.timeout = timeout
        self._ssl = _make_ssl_context(self.base_url, insecure, ca_file)
        # (kind, apiVersion) resolved via API discovery (resolve_kind),
        # memoized for the client's lifetime — discovery output only
        # changes on CRD install/uninstall, which warrants a process
        # restart anyway. Misses are cached with a TTL instead: a
        # misconfigured scaleTargetRef would otherwise re-walk the full
        # discovery surface every reconcile (every 10 s per bad HA),
        # while a short TTL still picks up a late-installed CRD.
        self._discovered: Dict[tuple, Tuple[str, str, bool]] = {}
        self._discovery_misses: Dict[tuple, float] = {}

    def _headers(self, content_type: Optional[str] = None) -> dict:
        headers = {"Accept": "application/json"}
        token = self._token
        if token is None and self._token_file:
            with open(self._token_file) as f:  # rotated by kubelet
                token = f.read().strip()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        timeout: Optional[float] = None,
    ) -> dict:
        url = f"{self.base_url}/{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers=self._headers(content_type if data is not None else None),
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl
            ) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as err:
            detail = err.read().decode(errors="replace")[:300]
            if err.code == 404:
                raise NotFoundError(f"{method} {path}: {detail}") from None
            if err.code == 409:
                raise ConflictError(f"{method} {path}: {detail}") from None
            raise RuntimeError(
                f"apiserver {method} {path} -> {err.code}: {detail}"
            ) from None
        return json.loads(payload) if payload else {}

    # -- collection paths --------------------------------------------------

    # -- kind -> resource mapping (discovery) ------------------------------

    def resolve_kind(
        self, kind: str, api_version: str = ""
    ) -> Tuple[str, str, bool]:
        """(api prefix, plural, namespaced) for a kind. The framework's
        own kinds come from the static table; anything else — e.g. an
        HA's scaleTargetRef pointing at a Deployment — is resolved via
        API discovery and memoized, the RESTMapper-over-discovery
        pattern the reference leans on (reference:
        autoscaler.go:196-237 parseGroupResource + RESTMapping).

        Resolution (and the memo) is keyed by (kind, apiVersion): two
        CRDs may legally share a kind across API groups, and a
        kind-only cache would scale whichever group was discovered
        first. The static table only short-circuits when the requested
        apiVersion matches (or is unspecified)."""
        static = RESOURCES.get(kind)
        if static is not None and (
            not api_version or static[0] == self._api_prefix(api_version)
        ):
            return static
        key = (kind, api_version)
        entry = self._discovered.get(key)
        if entry is not None:
            return entry
        miss_until = self._discovery_misses.get(key)
        if miss_until is not None and time.monotonic() < miss_until:
            raise NotFoundError(
                f"kind {kind!r} (apiVersion {api_version!r}) is not served "
                "by the apiserver (cached discovery miss; retries after "
                f"{DISCOVERY_MISS_TTL:.0f}s in case the CRD was installed)"
            )
        entry, degraded = self._discover_kind(kind, api_version)
        if entry is None:
            # only a DEFINITIVE miss (every group-version answered and
            # none serves the kind) is cached: a walk that skipped a
            # broken group may have skipped exactly the serving one, and
            # caching that would turn a momentary aggregated-API hiccup
            # into a DISCOVERY_MISS_TTL resolution outage
            if not degraded:
                self._discovery_misses[key] = (
                    time.monotonic() + DISCOVERY_MISS_TTL
                )
            raise NotFoundError(
                f"kind {kind!r} (apiVersion {api_version!r}) is not served "
                "by the apiserver (discovery found no matching resource"
                + (
                    "; some group-versions failed and were skipped"
                    if degraded
                    else ""
                )
                + ")"
            )
        self._discovered[key] = entry
        self._discovery_misses.pop(key, None)
        return entry

    @staticmethod
    def _api_prefix(api_version: str) -> str:
        # core group ("v1") lives under /api; everything else /apis
        return (
            f"api/{api_version}"
            if "/" not in api_version
            else f"apis/{api_version}"
        )

    def _discover_kind(self, kind: str, api_version: str):
        """Find the (group-version, plural, namespaced) serving `kind`.
        With an apiVersion (the CrossVersionObjectReference always has
        one) only that group-version's APIResourceList is consulted;
        without, every served group-version is walked (preferred
        versions first), plus core /api/v1. Returns (entry or None,
        degraded) — degraded means some group-version failed and was
        skipped, so a None result is NOT a definitive miss."""
        if api_version:
            prefixes = [self._api_prefix(api_version)]
            lenient = False  # the target group itself failing is an error
        else:
            prefixes = self._discovery_prefixes()
            # the blind walk must tolerate partial discovery failure: a
            # stale APIService (e.g. metrics.k8s.io with its backend
            # down answers 503) must not poison resolution of a kind
            # served by a healthy group — the RESTMapper posture
            lenient = True
        degraded = False
        for prefix in prefixes:
            entry, skipped = self._find_kind_in(prefix, kind, lenient)
            degraded = degraded or skipped
            if entry is not None:
                return entry, degraded
        return None, degraded

    def _discovery_prefixes(self) -> list:
        """Every served group-version (preferred versions first), plus
        core /api/v1 — the blind-discovery walk order."""
        prefixes = ["api/v1"]
        for group in self._request("GET", "apis").get("groups", []):
            preferred = (group.get("preferredVersion") or {}).get(
                "groupVersion"
            )
            versions = [
                v.get("groupVersion") for v in group.get("versions", [])
            ]
            ordered = [preferred] + [v for v in versions if v != preferred]
            prefixes.extend(f"apis/{gv}" for gv in ordered if gv)
        return prefixes

    def _find_kind_in(self, prefix: str, kind: str, lenient: bool = False):
        """(entry or None, skipped): skipped marks a group-version whose
        APIResourceList FAILED (not one that answered without the kind)."""
        try:
            payload = self._request("GET", prefix)
        except NotFoundError:
            return None, False  # group-version not served: definitive
        except RuntimeError as e:  # incl. ConflictError; 404 handled above
            if lenient:
                log.warning("discovery: skipping %s: %s", prefix, e)
                return None, True
            raise
        for res in payload.get("resources", []):
            # subresources list as "deployments/scale" — the primary
            # resource is the entry without a slash
            if res.get("kind") == kind and "/" not in res.get("name", ""):
                return (prefix, res["name"], bool(res.get("namespaced"))), False
        return None, False

    def _collection(
        self, kind: str, namespace: Optional[str], api_version: str = ""
    ) -> str:
        api, plural, namespaced = self.resolve_kind(kind, api_version)
        if namespaced and namespace is not None:
            return f"{api}/namespaces/{namespace}/{plural}"
        return f"{api}/{plural}"  # all-namespaces (or cluster-scoped)

    def _object_path(
        self, kind: str, namespace: str, name: str, api_version: str = ""
    ) -> str:
        return f"{self._collection(kind, namespace, api_version)}/{name}"

    # -- typed operations --------------------------------------------------

    # relist chunk size: at 100k+ pods a single unchunked LIST makes the
    # apiserver serialize the whole collection into one response (memory
    # spike on both ends, APF penalty); chunked LISTs stream pages via
    # the k8s continue-token protocol instead
    list_chunk_size = 5000

    def list(self, kind: str) -> Tuple[list, str]:
        """Chunked LIST (limit + continue tokens). The FIRST page's
        resourceVersion is the collection version the informer resumes
        its watch from — the continue protocol serves all pages at that
        same version, so the (list, rv) pair stays coherent."""
        base = self._collection(kind, None)
        objs = []
        rv = "0"
        token = None
        while True:
            path = f"{base}?limit={self.list_chunk_size}"
            if token:
                path += f"&continue={urllib.parse.quote(token)}"
            payload = self._request("GET", path)
            for item in payload.get("items", []):
                item.setdefault("kind", kind)
                objs.append(decode_from_read(item))
            meta = payload.get("metadata", {})
            if token is None:
                rv = meta.get("resourceVersion", "0")
            next_token = meta.get("continue")
            if next_token and next_token == token:
                # a misbehaving endpoint echoing the same token forever
                # would otherwise loop unbounded inside the informer's
                # resync; raising routes into its retry-with-backoff path
                raise RuntimeError(
                    f"list {kind}: continue token did not advance"
                )
            token = next_token
            if not token:
                return objs, rv

    def watch(
        self,
        kind: str,
        resource_version: str,
        handler: Callable[[str, object], None],
        stopped: threading.Event,
    ) -> str:
        """Stream one watch connection; returns the last-seen
        resourceVersion on EOF/stop so the caller can RESUME from it
        without a relist (clean EOFs are routine — real apiservers close
        watches every few minutes). Raises ConflictError on 410 Gone /
        ERROR events (caller must relist)."""
        path = (
            f"{self._collection(kind, None)}?watch=1"
            f"&resourceVersion={resource_version}"
        )
        url = f"{self.base_url}/{path}"
        req = urllib.request.Request(url, headers=self._headers())
        last_rv = resource_version
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ssl
            ) as resp:
                for line in resp:
                    if stopped.is_set():
                        return last_rv
                    line = line.strip()
                    if not line:
                        continue
                    event = json.loads(line)
                    etype = event.get("type")
                    if etype == "ERROR":
                        raise ConflictError(
                            f"watch {kind}: {event['object']}"
                        )
                    if etype not in ("ADDED", "MODIFIED", "DELETED"):
                        continue  # BOOKMARK etc.
                    doc = event["object"]
                    doc.setdefault("kind", kind)
                    rv = doc.get("metadata", {}).get("resourceVersion")
                    if rv is not None:
                        last_rv = rv
                    handler(
                        {
                            "ADDED": ADDED,
                            "MODIFIED": MODIFIED,
                            "DELETED": DELETED,
                        }[etype],
                        decode_from_read(doc),
                    )
        except (TimeoutError, _socket_timeout):
            # idle stream: resume from the last event, no relist needed
            return last_rv
        return last_rv

    def create(self, obj):
        kind = type(obj).__name__
        payload = self._request(
            "POST",
            self._collection(kind, obj.metadata.namespace),
            encode_for_write(obj),
        )
        payload.setdefault("kind", kind)
        return decode_from_read(payload)

    def update(self, obj):
        kind = type(obj).__name__
        payload = self._request(
            "PUT",
            self._object_path(
                kind, obj.metadata.namespace, obj.metadata.name
            ),
            encode_for_write(obj),
        )
        payload.setdefault("kind", kind)
        return decode_from_read(payload)

    def get(self, kind: str, namespace: str, name: str):
        payload = self._request(
            "GET", self._object_path(kind, namespace, name)
        )
        payload.setdefault("kind", kind)
        return decode_from_read(payload)

    def patch_status(self, obj, previous_status: Optional[dict] = None):
        """Merge-patch the status subresource. merge-patch only *sets* keys,
        so map entries removed locally (e.g. a reservedCapacity resource that
        disappeared) would otherwise linger upstream forever — pass the
        last-known upstream status to have vanished keys patched to null
        (JSON merge-patch's deletion marker, RFC 7386)."""
        kind = type(obj).__name__
        status = to_dict(obj).get("status", {})
        if previous_status:
            status = _null_vanished(previous_status, status)
        payload = self._request(
            "PATCH",
            self._object_path(
                kind, obj.metadata.namespace, obj.metadata.name
            )
            + "/status",
            {"status": status},
            content_type="application/merge-patch+json",
        )
        payload.setdefault("kind", kind)
        return decode_from_read(payload)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request(
            "DELETE", self._object_path(kind, namespace, name)
        )

    def get_scale(
        self, kind: str, namespace: str, name: str, api_version: str = ""
    ) -> Scale:
        payload = self._request(
            "GET",
            self._object_path(kind, namespace, name, api_version) + "/scale",
        )
        return Scale(
            namespace=namespace,
            name=name,
            spec_replicas=payload.get("spec", {}).get("replicas"),
            status_replicas=payload.get("status", {}).get("replicas", 0) or 0,
        )

    def update_scale(
        self, kind: str, scale: Scale, api_version: str = ""
    ) -> None:
        self._request(
            "PUT",
            self._object_path(kind, scale.namespace, scale.name, api_version)
            + "/scale",
            {
                "apiVersion": "autoscaling/v1",
                "kind": "Scale",
                "metadata": {
                    "name": scale.name,
                    "namespace": scale.namespace,
                },
                "spec": {"replicas": scale.spec_replicas},
            },
        )

    # -- leases (coordination.k8s.io) --------------------------------------

    def _lease_path(self, namespace: str, name: Optional[str] = None) -> str:
        path = f"{_LEASE_API}/namespaces/{namespace}/leases"
        return f"{path}/{name}" if name else path

    def get_lease(self, namespace: str, name: str) -> Lease:
        return self._decode_lease(
            self._request("GET", self._lease_path(namespace, name))
        )

    def create_lease(self, lease: Lease) -> Lease:
        return self._decode_lease(
            self._request(
                "POST",
                self._lease_path(lease.metadata.namespace),
                self._encode_lease(lease),
            )
        )

    def update_lease(self, lease: Lease) -> Lease:
        return self._decode_lease(
            self._request(
                "PUT",
                self._lease_path(
                    lease.metadata.namespace, lease.metadata.name
                ),
                self._encode_lease(lease),
            )
        )

    @staticmethod
    def _encode_lease(lease: Lease) -> dict:
        meta = {
            "name": lease.metadata.name,
            "namespace": lease.metadata.namespace,
        }
        if lease.metadata.resource_version:
            meta["resourceVersion"] = str(lease.metadata.resource_version)
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": lease.holder,
                "renewTime": _epoch_to_rfc3339(lease.renew_time),
                "leaseDurationSeconds": int(lease.lease_duration),
            },
        }

    @staticmethod
    def _decode_lease(doc: dict) -> Lease:
        from karpenter_tpu.api.core import ObjectMeta

        meta = doc.get("metadata", {})
        spec = doc.get("spec", {})
        renew = spec.get("renewTime")
        rv = meta.get("resourceVersion", 0) or 0
        try:
            rv = int(rv)
        except ValueError:  # opaque string rv — equality is all leases need
            pass
        return Lease(
            metadata=ObjectMeta(
                name=meta.get("name", ""),
                namespace=meta.get("namespace", "default"),
                uid=meta.get("uid", ""),
                resource_version=rv,
            ),
            holder=spec.get("holderIdentity", "") or "",
            renew_time=_rfc3339_to_epoch(renew) if renew else 0.0,
            lease_duration=float(
                spec.get("leaseDurationSeconds", 15) or 15
            ),
        )


class KubeStore:
    """Store facade over a kube-apiserver: informer mirror for reads and
    watches, REST for writes. Drop-in for Store across the framework."""

    def __init__(
        self,
        client: KubeClient,
        watch_kinds: Tuple[str, ...] = WATCHED_KINDS,
        resync_backoff: float = 2.0,
    ):
        self.client = client
        self._mirror = Store()
        self._lock = self._mirror._lock  # caches adopt under the same lock
        self._stopped = threading.Event()
        self._resync_backoff = resync_backoff
        self._threads: List[threading.Thread] = []
        for kind in watch_kinds:
            rv = self._resync(kind)
            thread = threading.Thread(
                target=self._watch_loop, args=(kind, rv), daemon=True
            )
            thread.start()
            self._threads.append(thread)

    # -- informer machinery ------------------------------------------------

    def _resync(self, kind: str) -> str:
        """Full relist: reconcile the mirror to the apiserver's current
        truth (apply changes, delete vanished objects)."""
        objs, rv = self.client.list(kind)
        seen = set()
        for obj in objs:
            seen.add((kind, obj.metadata.namespace, obj.metadata.name))
            self._mirror.apply_event(MODIFIED, obj)
        for key in self._mirror.keys(kind):
            if key not in seen:
                vanished = self._mirror.try_get(*key)
                if vanished is not None:
                    self._mirror.apply_event(DELETED, vanished)
        return rv

    def _watch_loop(self, kind: str, rv: str) -> None:
        """Keep one informer stream alive forever. Clean EOF / idle
        timeout resumes from the last-seen resourceVersion with NO relist
        (relists notify every object and would defeat the incremental
        feed); only a 410 Gone window expiry or a transport error forces
        a full resync — and a failed resync retries with backoff rather
        than ever letting the thread die on a stale mirror."""
        while not self._stopped.is_set():
            needs_resync = False
            try:
                rv = self.client.watch(
                    kind, rv, self._mirror.apply_event, self._stopped
                )
            except ConflictError:
                needs_resync = True  # 410 Gone: watch window expired
            except Exception as err:  # noqa: BLE001 — keep the informer up
                if self._stopped.is_set():
                    return
                log.warning("watch %s: %s; resyncing", kind, err)
                needs_resync = True
            while needs_resync and not self._stopped.is_set():
                try:
                    rv = self._resync(kind)
                    needs_resync = False
                except Exception:  # noqa: BLE001
                    time.sleep(self._resync_backoff)

    def close(self) -> None:
        self._stopped.set()

    # -- reads: the mirror --------------------------------------------------

    def get(self, kind: str, namespace: str, name: str):
        if kind == "Lease":
            return self.client.get_lease(namespace, name)
        return self._mirror.get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace=None, label_selector=None) -> list:
        return self._mirror.list(kind, namespace, label_selector)

    def keys(self, kind: str) -> list:
        return self._mirror.keys(kind)

    def pods_on_node(self, node_name: str) -> list:
        return self._mirror.pods_on_node(node_name)

    def watch(self, kind: Optional[str], callback: Callable) -> None:
        self._mirror.watch(kind, callback)

    # -- writes: the apiserver ----------------------------------------------

    @staticmethod
    def _stamp(obj, written):
        """Mirror the local Store's contract (store.py create/update): the
        CALLER's object is stamped with the server-assigned identity, so
        code that ignores the return value behaves identically against the
        in-memory store and a real apiserver."""
        obj.metadata.uid = written.metadata.uid
        obj.metadata.resource_version = written.metadata.resource_version
        obj.metadata.creation_timestamp = written.metadata.creation_timestamp
        return written

    def create(self, obj):
        if isinstance(obj, Lease):
            return self.client.create_lease(obj)
        return self._stamp(obj, self.client.create(obj))

    def update(self, obj):
        if isinstance(obj, Lease):
            return self.client.update_lease(obj)
        return self._stamp(obj, self.client.update(obj))

    def patch_status(self, obj):
        # the mirror holds the last-known upstream status: keys it has that
        # the local object dropped get explicit nulls so merge-patch deletes
        # them. A stale mirror at worst delays a deletion one tick —
        # level-triggered reconciles recompute the full status every time.
        mirrored = self._mirror.try_get(
            type(obj).__name__, obj.metadata.namespace, obj.metadata.name
        )
        previous = to_dict(mirrored).get("status") if mirrored else None
        return self.client.patch_status(obj, previous_status=previous)

    def delete(self, obj_or_kind, namespace=None, name=None) -> None:
        if isinstance(obj_or_kind, str):
            kind = obj_or_kind
        else:
            kind = type(obj_or_kind).__name__
            namespace = obj_or_kind.metadata.namespace
            name = obj_or_kind.metadata.name
        self.client.delete(kind, namespace, name)

    def get_scale(
        self, kind: str, namespace: str, name: str, api_version: str = ""
    ) -> Scale:
        return self.client.get_scale(kind, namespace, name, api_version)

    def update_scale(
        self, kind: str, scale: Scale, api_version: str = ""
    ) -> None:
        self.client.update_scale(kind, scale, api_version)
