"""Incremental columnar snapshot of pending pods — the device feed path.

SURVEY.md §7 hard part (d): at 100k pods the bin-pack device call is ~0.1 ms
but a naive host feed is seconds — store.list() deep-copies every Pod and a
Python loop re-encodes requests/tolerations/selectors EVERY tick. The
reference never solved this (its pending-capacity producer is a stub,
reference: pkg/metrics/producers/pendingcapacity/producer.go:29-31, and its
design doc concedes the naive form "scales linearly ... as the cluster
grows", docs/designs/DESIGN.md).

The TPU-first answer is the same one informers give the reference's Go
controllers (watch once, index incrementally — reference:
pkg/controllers/manager.go:73-79 pod index): subscribe to store watch
events and maintain the solver's input arrays *incrementally*:

- slot-allocated columnar arena: requests (float32 N×R), required-label
  bitset (bool N×L), toleration-shape id (int32 N), valid mask
- universes (resource names, selector label pairs, toleration shapes) grow
  in arrival order; when churn leaves the arena or the universes mostly
  dead (peak >> live), a compaction pass rebuilds both from the retained
  per-slot sparse records — amortized O(live), no store access, so costs
  track the LIVE pending set, not the historical peak
- a pod is parsed ONCE at its lifecycle event (Quantity → float, selector →
  bitset), not once per tick; per-tick feed cost is O(changed pods), and
  snapshot() is a bulk numpy copy
- downstream, the chain stays incremental all the way to the chip: the
  encoder's delta layer (pendingcapacity/encoder.SnapshotDeltaCache)
  splices only the changed rows and publishes a ResidentPlan, and the
  solver's device-resident fleet state (solver/resident.py) applies it
  as a batched scatter — an unchanged dedup set costs zero host encode
  AND zero host->device upload (docs/solver-service.md
  "Device-resident fleet state")

Intolerance vs the (node-derived) taint universe cannot be cached here —
taints belong to groups and change with nodes — so the cache stores each
pod's toleration SHAPE id; the per-tick solve computes one row per distinct
shape (fleets share a handful) and gathers rows by id.

The same encoder also serves the non-cached oracle path:
snapshot_from_pods() runs a detached (watch-free) cache over a pod list,
so there is exactly ONE encode implementation and the cached path can never
drift from the documented list semantics.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import affinity_shape as _affinity_shape
from karpenter_tpu.api.core import pod_affinity_shape as _pod_affinity_shape
from karpenter_tpu.api.core import preferred_shape as _preferred_shape
from karpenter_tpu.api.core import (
    soft_pod_affinity_shape as _soft_pod_affinity_shape,
)
from karpenter_tpu.api.core import soft_spread_shape as _soft_spread_shape
from karpenter_tpu.api.core import spread_shape as _spread_shape
from karpenter_tpu.api.core import selector_form_matches
from karpenter_tpu.store.store import DELETED, Store

# seed columns; extended resources append after in arrival order.
# (pendingcapacity.py's RESOURCES_BASE aliases this — single definition.)
BASE_RESOURCES = ("cpu", "memory")
RESOURCE_PODS = "pods"

_GROW = 2  # arena growth factor
_COMPACT_FACTOR = 4  # compact when peak > factor × live
_COMPACT_FLOOR = 256  # ...and peak is at least this big


def is_pending(pod) -> bool:
    """Unschedulable set: unbound and not yet running/finished (the
    reference's pending-pods definition, DESIGN.md 'Pending Pods')."""
    return not pod.spec.node_name and pod.status.phase in ("", "Pending")


def _intern(shapes: List[tuple], index: Dict[tuple, int], shape: tuple) -> int:
    """Shape-registry intern: one id per distinct canonical tuple; id 0
    is always the empty/unconstrained shape (seeded at arena reset)."""
    sid = index.get(shape)
    if sid is None:
        sid = len(shapes)
        index[shape] = sid
        shapes.append(shape)
    return sid


def _adopt_and_watch(store: Store, kind: str, on_event) -> None:
    """Seed from the store's current objects, then subscribe — both under
    the store lock so no event lands in the gap. The single definition of
    the watch-mirror init contract for every cache in this module."""
    with store._lock:
        for obj in store.list(kind):
            on_event("Added", obj)
        store.watch(kind, on_event)


@dataclass
class _SparsePod:
    """Per-slot retained encoding — enough to rebuild arenas on compaction
    without touching the store (no store-lock acquisition from the cache
    side, so lock order is strictly store → cache)."""

    requests: List[Tuple[str, float]]
    selector: List[Tuple[str, str]]
    shape: tuple
    tolerations: list
    priority: int = 0  # resolved scheduling priority (api/core)
    affinity: tuple = ()  # canonical required-node-affinity shape
    preferred: tuple = ()  # canonical preferred-node-affinity shape
    spread: tuple = ()  # canonical hard topology-spread shape
    anti: tuple = ()  # canonical self pod-(anti-)affinity shape
    soft_spread: tuple = ()  # canonical ScheduleAnyway spread shape
    soft_anti: tuple = ()  # canonical preferred self pod-(anti-)affinity
    labels: tuple = ()  # sorted pod label items (constraint-group membership)


class PendingPodCache:
    """Watch-maintained columnar arena of pending-pod solver inputs.

    store=None builds a DETACHED encoder (no watch, no adoption) used by
    snapshot_from_pods() — the oracle path reuses the exact same encode.
    """

    def __init__(
        self,
        store: Optional[Store] = None,
        capacity: int = 1024,
        default_priority: int = 0,
    ):
        # fleet default for pods naming an unknown PriorityClass (the
        # --default-priority knob); resolved spec.priority always wins
        self._default_priority = default_priority
        self._lock = threading.Lock()
        # generation counts MUTATIONS (upsert/remove/compact), not resets:
        # snapshot() memoizes on it, and downstream encode/device caches key
        # on it to skip re-encoding + re-transferring an unchanged fleet
        self._generation = 0
        self._snap_memo: Optional[Tuple[int, "PendingSnapshot"]] = None
        self._reset_arena(max(16, capacity))

        if store is not None:
            _adopt_and_watch(store, "Pod", self._on_event)

    def _reset_arena(self, capacity: int) -> None:
        self._resources: List[str] = list(BASE_RESOURCES)
        self._resource_index: Dict[str, int] = {
            r: i for i, r in enumerate(BASE_RESOURCES)
        }
        self._labels: List[Tuple[str, str]] = []
        self._label_index: Dict[Tuple[str, str], int] = {}
        self._shapes: List[tuple] = []
        self._shape_index: Dict[tuple, int] = {}
        self._shape_tolerations: List[list] = []
        # required-node-affinity shapes (api/core.affinity_shape tuples);
        # id 0 is the unconstrained shape so zeroed slots stay neutral
        self._affinity_shapes: List[tuple] = [()]
        self._affinity_index: Dict[tuple, int] = {(): 0}
        # preferred-node-affinity shapes (api/core.preferred_shape)
        self._preferred_shapes: List[tuple] = [()]
        self._preferred_index: Dict[tuple, int] = {(): 0}
        # hard topology-spread shapes (api/core.spread_shape)
        self._spread_shapes: List[tuple] = [()]
        self._spread_index: Dict[tuple, int] = {(): 0}
        # self pod-(anti-)affinity shapes (api/core.pod_affinity_shape)
        self._anti_shapes: List[tuple] = [()]
        self._anti_index: Dict[tuple, int] = {(): 0}
        # SOFT (scored, never constraining) shapes: ScheduleAnyway
        # spread + preferred self pod-(anti-)affinity
        self._soft_spread_shapes: List[tuple] = [()]
        self._soft_spread_index: Dict[tuple, int] = {(): 0}
        self._soft_anti_shapes: List[tuple] = [()]
        self._soft_anti_index: Dict[tuple, int] = {(): 0}
        # distinct pod label SETS (constraint-plane membership input;
        # id 0 = unlabeled). NOT part of the dedup key: label churn on
        # identical specs must not split rows for unconstrained fleets —
        # constraint-active encodes re-dedup with membership appended
        # (encoder._dedup_rows_constrained).
        self._label_sets: List[tuple] = [()]
        self._label_set_index: Dict[tuple, int] = {(): 0}
        # incremental shape-dedup: canonical pod key -> live slots with that
        # key. Maintained at event time so snapshot() emits (rep row,
        # multiplicity) pairs in O(distinct shapes) — the per-tick
        # np.unique over ALL rows it replaces was the top host cost of a
        # churned 100k-pod tick (~60 ms of argsort).
        self._dedup_slots: Dict[tuple, set] = {}
        self._slot_key: Dict[int, tuple] = {}

        self._requests = np.zeros(
            (capacity, len(self._resources) + 4), np.float32
        )
        self._required = np.zeros((capacity, 8), bool)
        self._priority = np.zeros(capacity, np.int32)
        self._shape_id = np.zeros(capacity, np.int32)
        self._affinity_id = np.zeros(capacity, np.int32)
        self._preferred_id = np.zeros(capacity, np.int32)
        self._spread_id = np.zeros(capacity, np.int32)
        self._anti_id = np.zeros(capacity, np.int32)
        self._soft_spread_id = np.zeros(capacity, np.int32)
        self._soft_anti_id = np.zeros(capacity, np.int32)
        self._labels_id = np.zeros(capacity, np.int32)
        self._valid = np.zeros(capacity, bool)

        self._slot: Dict[Tuple[str, str], int] = {}
        self._sparse: Dict[int, _SparsePod] = {}
        self._free: List[int] = []
        self._hi = 0  # slots [0, _hi) have ever been used

    # -- watch path --------------------------------------------------------

    def _on_event(self, event: str, pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if event == DELETED or not is_pending(pod):
                self._remove(key)
            else:
                self._upsert(key, pod)

    def _remove(self, key) -> None:
        slot = self._slot.pop(key, None)
        if slot is None:
            return
        self._generation += 1
        self._valid[slot] = False
        self._requests[slot, :] = 0.0
        self._required[slot, :] = False
        self._priority[slot] = 0
        self._shape_id[slot] = 0
        self._affinity_id[slot] = 0
        self._preferred_id[slot] = 0
        self._spread_id[slot] = 0
        self._anti_id[slot] = 0
        self._soft_spread_id[slot] = 0
        self._soft_anti_id[slot] = 0
        self._labels_id[slot] = 0
        self._sparse.pop(slot, None)
        self._dedup_discard(slot)
        self._free.append(slot)

    def _dedup_discard(self, slot: int) -> None:
        dedup_key = self._slot_key.pop(slot, None)
        if dedup_key is None:
            return
        slots = self._dedup_slots.get(dedup_key)
        if slots is not None:
            slots.discard(slot)
            if not slots:
                del self._dedup_slots[dedup_key]

    def _upsert(self, key, pod) -> None:
        from karpenter_tpu.api.core import effective_priority

        sparse = _SparsePod(
            # effective_requests: the SCHEDULER's fit semantics (init
            # containers max'd against the container sum, overhead added) —
            # the bin-pack must see what a real kube-scheduler would fit,
            # or the scale-up signal undersizes pods with heavy init phases
            requests=[
                (resource, quantity.to_float())
                for resource, quantity in pod.effective_requests().items()
                if quantity.to_float() > 0 and resource != RESOURCE_PODS
            ],
            selector=sorted(pod.spec.node_selector.items()),
            shape=tuple(
                sorted(
                    (t.key, t.operator, t.value, t.effect)
                    for t in pod.spec.tolerations
                )
            ),
            tolerations=list(pod.spec.tolerations),
            affinity=_affinity_shape(pod.spec.affinity),
            preferred=_preferred_shape(pod.spec.affinity),
            spread=_spread_shape(
                pod.spec.topology_spread_constraints,
                pod.metadata.namespace,
                pod.metadata.labels,
            ),
            anti=_pod_affinity_shape(
                pod.spec.affinity,
                pod.metadata.labels,
                pod.metadata.namespace,
            ),
            soft_spread=_soft_spread_shape(
                pod.spec.topology_spread_constraints,
                pod.metadata.namespace,
                pod.metadata.labels,
            ),
            soft_anti=_soft_pod_affinity_shape(
                pod.spec.affinity,
                pod.metadata.labels,
                pod.metadata.namespace,
            ),
            priority=effective_priority(
                pod, default=self._default_priority
            ),
            labels=tuple(sorted((pod.metadata.labels or {}).items())),
        )
        slot = self._slot.get(key)
        if slot is None:
            slot = self._alloc()
            self._slot[key] = slot
        self._generation += 1
        self._encode(slot, sparse)

    def _encode(self, slot: int, sparse: _SparsePod) -> None:
        self._requests[slot, :] = 0.0
        self._required[slot, :] = False
        for resource, value in sparse.requests:
            idx = self._resource_col(resource)
            self._requests[slot, idx] = value
        for item in sparse.selector:
            # resolve the column BEFORE subscripting: _label_col may
            # replace self._required with a grown copy
            idx = self._label_col(item)
            self._required[slot, idx] = True
        shape_id = self._shape_index.get(sparse.shape)
        if shape_id is None:
            shape_id = len(self._shapes)
            self._shape_index[sparse.shape] = shape_id
            self._shapes.append(sparse.shape)
            self._shape_tolerations.append(sparse.tolerations)
        self._shape_id[slot] = shape_id
        self._affinity_id[slot] = _intern(
            self._affinity_shapes, self._affinity_index, sparse.affinity
        )
        self._preferred_id[slot] = _intern(
            self._preferred_shapes, self._preferred_index, sparse.preferred
        )
        self._spread_id[slot] = _intern(
            self._spread_shapes, self._spread_index, sparse.spread
        )
        self._anti_id[slot] = _intern(
            self._anti_shapes, self._anti_index, sparse.anti
        )
        self._soft_spread_id[slot] = _intern(
            self._soft_spread_shapes,
            self._soft_spread_index,
            sparse.soft_spread,
        )
        self._soft_anti_id[slot] = _intern(
            self._soft_anti_shapes,
            self._soft_anti_index,
            sparse.soft_anti,
        )
        self._labels_id[slot] = _intern(
            self._label_sets, self._label_set_index, sparse.labels
        )
        self._priority[slot] = sparse.priority
        self._valid[slot] = True
        self._sparse[slot] = sparse
        # dedup maintenance: two slots share a key iff their canonical
        # sparse encodings match, which (with stable universe columns)
        # guarantees identical arena rows. Resource order in `requests` is
        # dict-iteration order, so sort for canonicality; selector/shape
        # are already sorted at build time. Priority is part of shape
        # identity: it drives steering and evictability, so equal-spec
        # pods of different PriorityClasses must not collapse.
        dedup_key = (
            tuple(sorted(sparse.requests)),
            tuple(sparse.selector),
            sparse.shape,
            sparse.affinity,
            sparse.preferred,
            sparse.spread,
            sparse.anti,
            sparse.soft_spread,
            sparse.soft_anti,
            sparse.priority,
        )
        if self._slot_key.get(slot) != dedup_key:
            self._dedup_discard(slot)
            self._slot_key[slot] = dedup_key
            self._dedup_slots.setdefault(dedup_key, set()).add(slot)

    # -- compaction --------------------------------------------------------

    def _needs_compaction(self) -> bool:
        """O(1) unless a cheap precondition trips: the O(live) live-set
        scans below only run when a universe has already crossed the
        absolute floor — snapshot() on a healthy cache stays a bulk copy."""
        live = len(self._slot)
        if self._hi >= _COMPACT_FLOOR and self._hi > _COMPACT_FACTOR * live:
            return True
        for registry, ids in (
            (self._shapes, self._shape_id),
            (self._affinity_shapes, self._affinity_id),
            (self._preferred_shapes, self._preferred_id),
            (self._spread_shapes, self._spread_id),
            (self._anti_shapes, self._anti_id),
            (self._soft_spread_shapes, self._soft_spread_id),
            (self._soft_anti_shapes, self._soft_anti_id),
            (self._label_sets, self._labels_id),
        ):
            if len(registry) >= _COMPACT_FLOOR:
                live_ids = len(
                    {int(ids[s]) for s in self._slot.values()}
                )
                if len(registry) > _COMPACT_FACTOR * max(1, live_ids):
                    return True
        if len(self._labels) >= _COMPACT_FLOOR:
            live_labels: set = set()
            for sparse in self._sparse.values():
                live_labels.update(sparse.selector)
            if len(self._labels) > _COMPACT_FACTOR * max(1, len(live_labels)):
                return True
        return False

    def _compact(self) -> None:
        """Rebuild arenas + universes from live sparse records: O(live),
        restoring cost proportional to the live pending set after a peak
        (incident) has drained or per-job universes have churned."""
        records = [
            (key, self._sparse[slot]) for key, slot in self._slot.items()
        ]
        capacity = 16
        while capacity < 2 * max(1, len(records)):
            capacity *= _GROW
        self._generation += 1  # row order / universes may change
        self._reset_arena(capacity)
        for key, sparse in records:
            slot = self._alloc()
            self._slot[key] = slot
            self._encode(slot, sparse)

    # -- arena management --------------------------------------------------

    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        if self._hi == self._requests.shape[0]:
            self._requests = self._grow_rows(self._requests)
            self._required = self._grow_rows(self._required)
            self._priority = self._grow_rows(self._priority)
            self._shape_id = self._grow_rows(self._shape_id)
            self._affinity_id = self._grow_rows(self._affinity_id)
            self._preferred_id = self._grow_rows(self._preferred_id)
            self._spread_id = self._grow_rows(self._spread_id)
            self._anti_id = self._grow_rows(self._anti_id)
            self._soft_spread_id = self._grow_rows(self._soft_spread_id)
            self._soft_anti_id = self._grow_rows(self._soft_anti_id)
            self._labels_id = self._grow_rows(self._labels_id)
            self._valid = self._grow_rows(self._valid)
        slot = self._hi
        self._hi += 1
        return slot

    @staticmethod
    def _grow_rows(arr: np.ndarray) -> np.ndarray:
        shape = (arr.shape[0] * _GROW, *arr.shape[1:])
        out = np.zeros(shape, arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    def _resource_col(self, resource: str) -> int:
        idx = self._resource_index.get(resource)
        if idx is None:
            idx = len(self._resources)
            self._resource_index[resource] = idx
            self._resources.append(resource)
            if idx == self._requests.shape[1]:
                self._requests = self._grow_cols(self._requests)
        return idx

    def _label_col(self, item: Tuple[str, str]) -> int:
        idx = self._label_index.get(item)
        if idx is None:
            idx = len(self._labels)
            self._label_index[item] = idx
            self._labels.append(item)
            if idx == self._required.shape[1]:
                self._required = self._grow_cols(self._required)
        return idx

    @staticmethod
    def _grow_cols(arr: np.ndarray) -> np.ndarray:
        out = np.zeros((arr.shape[0], arr.shape[1] * _GROW), arr.dtype)
        out[:, : arr.shape[1]] = arr
        return out

    # -- solve-side read ---------------------------------------------------

    def snapshot(self) -> "PendingSnapshot":
        """Bulk-copy the live region; O(pending pods) numpy memcpy, no
        Python-per-pod work. Compacts first when peak >> live.

        Memoized per generation: an unchanged arena returns the SAME
        snapshot object, so callers can key their own derived caches
        (encoded solver inputs, device-resident buffers) on identity or
        on `snapshot.generation`. This identity chain is load-bearing:
        snapshot identity -> delta-cache hit -> same BinPackInputs
        object -> ResidentFleetState identity hit (zero upload), so
        snapshot() must never return equal-but-distinct objects for an
        unchanged generation."""
        with self._lock:
            if self._needs_compaction():
                self._compact()
            if (
                self._snap_memo is not None
                and self._snap_memo[0] == self._generation
            ):
                return self._snap_memo[1]
            hi = self._hi
            # one items() walk so keys/reps/weights share the dict order:
            # dedup_keys[i] is the CANONICAL sparse key of the shape that
            # dedup_idx[i]/dedup_weight[i] describe — the stable identity
            # the encoder's delta layer diffs consecutive snapshots on
            # (slot ids and universe ids churn; the canonical key doesn't)
            dedup_items = list(self._dedup_slots.items())
            reps = np.fromiter(
                (next(iter(s)) for _, s in dedup_items),
                np.intp,
                len(dedup_items),
            )
            weights = np.fromiter(
                (len(s) for _, s in dedup_items),
                np.int32,
                len(dedup_items),
            )
            snap = PendingSnapshot(
                requests=self._requests[:hi, : len(self._resources)].copy(),
                required=self._required[:hi, : len(self._labels)].copy(),
                priority=self._priority[:hi].copy(),
                shape_id=self._shape_id[:hi].copy(),
                valid=self._valid[:hi].copy(),
                resources=list(self._resources),
                labels=list(self._labels),
                shape_tolerations=[list(t) for t in self._shape_tolerations],
                generation=self._generation,
                dedup_idx=reps,
                dedup_weight=weights,
                dedup_keys=tuple(k for k, _ in dedup_items),
                affinity_id=self._affinity_id[:hi].copy(),
                affinity_shapes=list(self._affinity_shapes),
                preferred_id=self._preferred_id[:hi].copy(),
                preferred_shapes=list(self._preferred_shapes),
                spread_id=self._spread_id[:hi].copy(),
                spread_shapes=list(self._spread_shapes),
                anti_id=self._anti_id[:hi].copy(),
                anti_shapes=list(self._anti_shapes),
                soft_spread_id=self._soft_spread_id[:hi].copy(),
                soft_spread_shapes=list(self._soft_spread_shapes),
                soft_anti_id=self._soft_anti_id[:hi].copy(),
                soft_anti_shapes=list(self._soft_anti_shapes),
                labels_id=self._labels_id[:hi].copy(),
                label_sets=list(self._label_sets),
            )
            self._snap_memo = (self._generation, snap)
            return snap

    def __len__(self) -> int:
        with self._lock:
            return len(self._slot)


class NodeMirror:
    """Watch-maintained mirror of Node objects with memoized group
    profiles.

    _group_profile (pendingcapacity.py) is O(nodes) per selector with
    Python-level label matching; recomputing it for every producer every
    5 s tick costs O(producers × nodes) even when no node changed. The
    mirror holds the store's Node set current via watch events and
    memoizes profile(selector) until ANY node event invalidates (node
    churn is orders slower than the reconcile tick). Lock order is
    strictly store → mirror: events only touch mirror state, profile
    computation never touches the store.
    """

    def __init__(self, store: Store, profile_fn):
        self._lock = threading.Lock()
        self._profile_fn = profile_fn  # (nodes, selector) -> profile
        self._nodes: Dict[Tuple[str, str], object] = {}
        self._memo: Dict[tuple, object] = {}
        self._version = 0
        _adopt_and_watch(store, "Node", self._on_event)

    def _on_event(self, event: str, node) -> None:
        key = (node.metadata.namespace, node.metadata.name)
        with self._lock:
            if event == DELETED:
                self._nodes.pop(key, None)
            else:
                self._nodes[key] = node
            self._memo.clear()
            self._version += 1

    @property
    def version(self) -> int:
        """Node-event counter; bumps on any node churn. Lets callers key
        profile-derived caches (encoded group arrays) on it."""
        with self._lock:
            return self._version

    def nodes(self, selector: Optional[Dict[str, str]] = None) -> list:
        """Current node objects, optionally filtered by label selector.
        Returned refs are safe to read: event-delivered copies are never
        mutated in place."""
        from karpenter_tpu.api.core import matches_selector

        with self._lock:
            values = list(self._nodes.values())
        if selector is None:
            return values
        return [
            n for n in values if matches_selector(n.metadata.labels, selector)
        ]

    def profile(self, selector: Dict[str, str]):
        key = tuple(sorted(selector.items()))
        # the O(nodes) profile pass runs OUTSIDE the mirror lock: watch
        # callbacks (which run under the store lock) must never wait on a
        # profile recomputation, or every store operation stalls behind it.
        # Event-delivered node copies are never mutated in place, so
        # computing over a snapshot of the refs is safe.
        with self._lock:
            cached = self._memo.get(key)
            if cached is not None:
                return cached
            nodes = list(self._nodes.values())
            version = self._version
        profile = self._profile_fn(nodes, selector)
        with self._lock:
            if self._version == version:
                self._memo[key] = profile
            # stale (a node event landed mid-compute): return this tick's
            # consistent-at-read value uncached; the next tick recomputes
        return profile


class ReservationsCache:
    """Watch-maintained per-node reserved-resource sums — the incremental
    feed for the ReservedCapacity producer (reference hot loop #2,
    SURVEY.md §3.5: O(nodes + pods) exact Quantity additions per 5 s tick).

    Every BOUND pod's container requests (and its 1 'pods' slot) are added
    to its node's running total exactly once, at its lifecycle event;
    rebinding/resize/delete applies the exact inverse (Fraction arithmetic
    is exact, so incremental add/subtract equals a fresh sum). A tick then
    reads O(nodes-in-group) cached sums instead of iterating every pod.

    Display-format caveat: Quantity.add adopts the FIRST non-zero
    operand's format, so in a fleet mixing formats for one resource
    (e.g. "1Gi" and "1000M" memory) the rendered status string may pick a
    different (value-equal) canonical form than a fresh sum would.
    """

    def __init__(self, store: Store):
        from karpenter_tpu.api.core import RESOURCE_PODS as _PODS
        from karpenter_tpu.utils.quantity import Quantity

        self._lock = threading.Lock()
        self._quantity = Quantity
        self._pods_resource = _PODS
        # pod key -> (node_name, {resource: Quantity incl. the pods slot})
        self._pod_records: Dict[Tuple[str, str], Tuple[str, dict]] = {}
        # node name -> {resource: Quantity}
        self._node_sums: Dict[str, dict] = {}
        _adopt_and_watch(store, "Pod", self._on_event)

    def _record_for(self, pod) -> Optional[Tuple[str, dict]]:
        if not pod.spec.node_name:
            return None
        # Pod.requests() is THE accumulation semantics (container-level
        # only, reference reservations.go); the cache must never drift
        requests = pod.requests()
        requests[self._pods_resource] = self._quantity.parse("1")
        return (pod.spec.node_name, requests)

    def _on_event(self, event: str, pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        new = None if event == DELETED else self._record_for(pod)
        with self._lock:
            old = self._pod_records.pop(key, None)
            if old is not None:
                node, requests = old
                sums = self._node_sums.get(node)
                if sums is not None:
                    for resource, quantity in requests.items():
                        sums[resource] = sums[resource].sub(quantity)
                    if all(q.value == 0 for q in sums.values()):
                        # node drained (or deleted): drop the entry, or a
                        # node-churning fleet leaks one dict per node name
                        # ever seen
                        del self._node_sums[node]
            if new is not None:
                self._pod_records[key] = new
                node, requests = new
                sums = self._node_sums.setdefault(node, {})
                for resource, quantity in requests.items():
                    current = sums.get(resource)
                    sums[resource] = (
                        quantity if current is None else current.add(quantity)
                    )

    def reserved_on(self, node_names) -> dict:
        """{resource: Quantity} summed over the given nodes (exact)."""
        with self._lock:
            totals: dict = {}
            for name in node_names:
                for resource, quantity in self._node_sums.get(
                    name, {}
                ).items():
                    current = totals.get(resource)
                    totals[resource] = (
                        quantity
                        if current is None
                        else current.add(quantity)
                    )
            return totals


def is_counted(pod) -> bool:
    """Occupancy set: pods BOUND to a node and not terminal — the pods
    the kube-scheduler counts when evaluating topology spread skew and
    inter-pod (anti-)affinity domains against an incoming pod. Assigned-
    but-still-Pending pods count (they hold their domain); Succeeded/
    Failed pods don't block a domain the scheduler would reuse."""
    return bool(pod.spec.node_name) and pod.status.phase not in (
        "Succeeded",
        "Failed",
    )


class ScheduledOccupancy:
    """Watch-maintained census of SCHEDULED pods, grouped by
    (namespace, exact label set) with per-node counts — the existing-pod
    side of topology-spread skew and self-(anti-)affinity domain
    occupancy (producers/pendingcapacity.DomainCensus).

    Shape: {namespace: {labels_items_tuple: {node_name: count}}}.
    Replicated workloads collapse to one label group per namespace
    (plus one per pod for per-pod labels like the StatefulSet pod-name
    label). Event-time cost is O(1 + registered views) per pod
    transition.

    MATERIALIZED VIEWS (`view_counts`): per-pod-unique labels fragment
    a 100k-replica StatefulSet into 100k label groups, so answering a
    selector by scanning groups costs ~600 ms per occupancy epoch —
    over the tick budget by itself. Instead, each distinct query
    selector registers a view {node: matching-pod count}, built ONCE by
    a scan and then maintained incrementally at event time (each bound
    pod transition evaluates the pod's labels against the registered
    selector forms — a fleet-scale-constant set, LRU-capped). Queries
    read the view: O(nodes with matching pods), never O(label groups).

    Readers MUST use view() (raw groups) or view_counts(); the lock is
    held for the (short) duration of either. store=None builds a
    detached census (occupancy_from_pods).
    """

    # registered selector views are LRU-capped: every event updates the
    # views of ITS namespace, so a leak of stale selectors would tax
    # the event path. Above the cap (more distinct live (namespace,
    # selector) pairs than this, queried every solve) eviction thrashes
    # and each rebuild is a group scan under the lock — view_evictions
    # (published as karpenter_runtime_census_view_evictions_total)
    # makes that visible instead of silent.
    VIEW_CAP = 1024

    def __init__(self, store: Optional[Store] = None):
        self._lock = threading.Lock()
        self._generation = 0
        self._spaces: Dict[str, Dict[tuple, Dict[str, int]]] = {}
        # pod key -> (namespace, labels_items, node_name) for exact undo
        self._pods: Dict[Tuple[str, str], Tuple[str, tuple, str]] = {}
        # (namespace, selector form) -> {node: matching pod count}
        self._views: Dict[tuple, Dict[str, int]] = {}
        self._views_by_ns: Dict[str, Dict[tuple, Dict[str, int]]] = {}
        self._view_clock = 0
        self._view_used: Dict[tuple, int] = {}
        # cumulative LRU evictions — cap-thrash observability
        self.view_evictions = 0
        if store is not None:
            _adopt_and_watch(store, "Pod", self._on_event)

    def _view_delta(self, namespace, labels_items, node, delta) -> None:
        forms = self._views_by_ns.get(namespace)
        if not forms:
            return
        labels = dict(labels_items)
        for form, view in forms.items():
            if not selector_form_matches(form, labels):
                continue
            count = view.get(node, 0) + delta
            if count > 0:
                view[node] = count
            else:
                view.pop(node, None)

    def _on_event(self, event: str, pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        entry = None
        if event != DELETED and is_counted(pod):
            entry = (
                pod.metadata.namespace,
                tuple(sorted(pod.metadata.labels.items())),
                pod.spec.node_name,
            )
        with self._lock:
            prev = self._pods.get(key)
            if prev == entry:
                return
            self._generation += 1
            if prev is not None:
                namespace, labels, node = prev
                groups = self._spaces.get(namespace, {})
                nodes = groups.get(labels)
                if nodes is not None:
                    count = nodes.get(node, 0) - 1
                    if count > 0:
                        nodes[node] = count
                    else:
                        nodes.pop(node, None)
                        if not nodes:
                            del groups[labels]
                            if not groups:
                                del self._spaces[namespace]
                self._view_delta(namespace, labels, node, -1)
            if entry is None:
                self._pods.pop(key, None)
            else:
                self._pods[key] = entry
                namespace, labels, node = entry
                nodes = self._spaces.setdefault(namespace, {}).setdefault(
                    labels, {}
                )
                nodes[node] = nodes.get(node, 0) + 1
                self._view_delta(namespace, labels, node, +1)

    @property
    def generation(self) -> int:
        """Mutation counter — downstream query memos key on it."""
        with self._lock:
            return self._generation

    def namespace_names(self) -> set:
        """Namespaces holding scheduled pods — the conservative
        namespaceSelector fallback scope (DomainCensus)."""
        with self._lock:
            return set(self._spaces)

    @contextlib.contextmanager
    def view(self):
        """(generation, {namespace: {labels_items: {node: count}}})
        under the census lock — treat as read-only, don't retain past
        the with-block."""
        with self._lock:
            yield self._generation, self._spaces

    def _view_locked(self, namespace: str, sel_form: tuple) -> dict:
        """Resolve-or-build one view; caller holds the lock."""
        key = (namespace, sel_form)
        self._view_clock += 1
        view = self._views.get(key)
        if view is None:
            view = {}
            for labels_items, nodes in self._spaces.get(
                namespace, {}
            ).items():
                if selector_form_matches(sel_form, dict(labels_items)):
                    for node, n in nodes.items():
                        view[node] = view.get(node, 0) + n
            self._views[key] = view
            self._views_by_ns.setdefault(namespace, {})[sel_form] = view
            if len(self._views) > self.VIEW_CAP:
                evict = min(
                    (k for k in self._views if k != key),
                    key=lambda k: self._view_used.get(k, 0),
                )
                del self._views[evict]
                self._views_by_ns.get(evict[0], {}).pop(evict[1], None)
                self._view_used.pop(evict, None)
                self.view_evictions += 1
        self._view_used[key] = self._view_clock
        return view

    def view_counts(
        self, namespace: str, sel_form: tuple
    ) -> Tuple[int, Dict[str, int]]:
        """(generation, {node: count of scheduled pods matching the
        canonical selector form}) — the materialized-view read. First
        use of a selector builds its view by one scan (under the lock:
        consistency beats a one-time stall); every later read is a
        small copy kept current by the event path."""
        with self._lock:
            return self._generation, dict(
                self._view_locked(namespace, sel_form)
            )

    def view_counts_many(
        self, namespace: str, sel_forms
    ) -> Tuple[int, List[Dict[str, int]]]:
        """view_counts for several selectors under ONE lock hold — the
        results are a single-generation-consistent set (a pod event
        landing between per-form reads could otherwise show a moved
        replica on neither node, r3 code review)."""
        with self._lock:
            return self._generation, [
                dict(self._view_locked(namespace, form))
                for form in sel_forms
            ]


def occupancy_from_pods(pods) -> ScheduledOccupancy:
    """Oracle path: one-shot census of a pod list through the SAME
    accounting the watch-maintained census uses (detached mode)."""
    census = ScheduledOccupancy(store=None)
    for pod in pods:
        census._on_event("Added", pod)
    return census


class ProducerSelectorIndex:
    """Watch-maintained {key: (node_selector, node_group_ref,
    constraint_groups)} of every pendingCapacity MetricsProducer — the
    solve needs ONLY the selector, scale-from-zero ref, and declared
    constraint groups of non-due producers (their status writes land on
    discarded copies anyway; gauges are keyed by name/namespace), so
    listing + deep-copying every producer object per tick is
    avoidable."""

    def __init__(self, store: Store):
        self._lock = threading.Lock()
        self._specs: Dict[
            Tuple[str, str], Tuple[Dict[str, str], str, tuple]
        ] = {}
        _adopt_and_watch(store, "MetricsProducer", self._on_event)

    def _on_event(self, event: str, mp) -> None:
        key = (mp.metadata.namespace, mp.metadata.name)
        selector, ref, constraints = None, "", ()
        if event != DELETED and mp.spec.pending_capacity is not None:
            selector = mp.spec.pending_capacity.node_selector
            ref = getattr(mp.spec.pending_capacity, "node_group_ref", "")
            constraints = tuple(
                getattr(mp.spec.pending_capacity, "constraints", None)
                or ()
            )
            try:
                selector = dict(selector)
            except TypeError:
                # poisoned spec (e.g. null selector): index it verbatim —
                # a watch callback must NEVER raise (it runs under the
                # store's notify path, shared by every watcher), and the
                # per-row guard in solve_pending contains the blast radius
                # to this one producer at solve time
                pass
        with self._lock:
            if event == DELETED or mp.spec.pending_capacity is None:
                self._specs.pop(key, None)
            else:
                self._specs[key] = (selector, ref, constraints)

    def items(
        self,
    ) -> List[Tuple[Tuple[str, str], Tuple[Dict[str, str], str, tuple]]]:
        """(key, (selector, node_group_ref, constraint_groups)) in
        deterministic (namespace, name) order — the group-axis order of
        the solve."""
        with self._lock:
            return sorted(self._specs.items())


class PendingFeed:
    """The full incremental feed for the pending-pods solve: pod arena +
    node profiles + producer selectors, all watch-maintained. One object
    so the factory wires one thing and solve_pending takes one seam."""

    def __init__(
        self, store: Store, profile_fn, node_mirror=None,
        default_priority: int = 0,
    ):
        self.pods = PendingPodCache(
            store, default_priority=default_priority
        )
        self.nodes = (
            node_mirror
            if node_mirror is not None
            else NodeMirror(store, profile_fn)
        )
        self.producers = ProducerSelectorIndex(store)
        # existing-pod domain occupancy for spread/anti fidelity; the
        # solve path lazily attaches its memoizing DomainCensus here
        self.occupancy = ScheduledOccupancy(store)
        self.census = None
        # owned by the feed, WRITTEN by the solve path
        # (metrics/producers/pendingcapacity.solve_pending): memoizes the
        # last (fingerprint, BinPackInputs) so an unchanged fleet reuses
        # the same inputs OBJECT and the solver's identity-keyed device
        # cache skips the host->device transfer. The fingerprint covers
        # pods.snapshot().generation, nodes.version, the producer
        # (selector, nodeGroupRef) set, and the RESOLVED scale-from-zero
        # template profiles — so any reset/replacement of those caches,
        # and any provider-template change (within the resolver's TTL),
        # invalidates it naturally.
        self.encode_memo: Optional[tuple] = None


def snapshot_from_pods(pods) -> "PendingSnapshot":
    """Oracle path: one-shot encode of a pod list through the SAME encoder
    the watch-maintained cache uses (detached mode — no store, no watch)."""
    cache = PendingPodCache(store=None, capacity=max(16, len(pods)))
    for pod in pods:
        if is_pending(pod):
            cache._upsert(
                (pod.metadata.namespace, pod.metadata.name), pod
            )
    return cache.snapshot()


@dataclass(slots=True, eq=False, repr=False)  # ndarray fields: identity eq,
class PendingSnapshot:                        # no 100k-row reprs in logs
    requests: np.ndarray
    required: np.ndarray
    shape_id: np.ndarray
    valid: np.ndarray
    resources: List[str]
    labels: List[Tuple[str, str]]
    shape_tolerations: List[list]
    generation: int = 0  # arena mutation counter at snapshot time
    # incremental dedup (None on hand-built snapshots: _dedup_rows then
    # falls back to np.unique over all rows): representative row index +
    # multiplicity per distinct live pod shape, unordered — the encoder
    # canonicalizes order by row bytes
    dedup_idx: Optional[np.ndarray] = None
    dedup_weight: Optional[np.ndarray] = None
    # canonical sparse dedup keys aligned with dedup_idx/dedup_weight:
    # the shape identity that survives slot reuse, universe growth, and
    # compaction — what the encoder's delta layer matches rows on across
    # consecutive snapshots. None on hand-built snapshots.
    dedup_keys: Optional[tuple] = None
    # resolved scheduling priority per row (api/core.effective_priority;
    # part of the dedup identity). None on hand-built snapshots = every
    # row priority 0 — the encoder then emits NO priority operand, so
    # priority-free fleets solve exactly as before.
    priority: Optional[np.ndarray] = None
    # required node affinity: per-row shape id into affinity_shapes
    # (canonical api/core.affinity_shape tuples; id 0 = unconstrained).
    # None on hand-built snapshots = no pod constrains affinity.
    affinity_id: Optional[np.ndarray] = None
    affinity_shapes: Optional[List[tuple]] = None
    # preferred node affinity (api/core.preferred_shape; id 0 = none)
    preferred_id: Optional[np.ndarray] = None
    preferred_shapes: Optional[List[tuple]] = None
    # hard topology spread (api/core.spread_shape; id 0 = unconstrained)
    spread_id: Optional[np.ndarray] = None
    spread_shapes: Optional[List[tuple]] = None
    # self pod-(anti-)affinity (api/core.pod_affinity_shape; id 0 = none)
    anti_id: Optional[np.ndarray] = None
    anti_shapes: Optional[List[tuple]] = None
    # SOFT (scored) shapes: ScheduleAnyway spread + preferred self
    # pod-(anti-)affinity (api/core.soft_{spread,pod_affinity}_shape)
    soft_spread_id: Optional[np.ndarray] = None
    soft_spread_shapes: Optional[List[tuple]] = None
    soft_anti_id: Optional[np.ndarray] = None
    soft_anti_shapes: Optional[List[tuple]] = None
    # pod label sets (constraint-group membership): per-row id into
    # label_sets (id 0 = unlabeled). None on hand-built snapshots = no
    # membership data, constraint groups match nothing.
    labels_id: Optional[np.ndarray] = None
    label_sets: Optional[List[tuple]] = None
