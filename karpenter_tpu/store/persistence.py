"""Durable store: write-ahead log + snapshot, the etcd role.

The reference has no custom persistence because all durable state lives in
CRD spec/status *in etcd* — controllers are stateless and resume by
re-listing on start (reference: SURVEY.md §5 checkpoint/resume;
controller-runtime informers re-list; the only memory between ticks is
status fields like LastScaleTime, pkg/autoscaler/autoscaler.go:111).

The TPU build's in-memory Store (store/store.py) replaces the apiserver bus,
so it must also replace etcd's durability: DurableStore journals every
mutation to a JSONL write-ahead log and periodically compacts into a full
snapshot, both under the store lock so the on-disk order is exactly the
resourceVersion order. Recovery = load snapshot, replay WAL, tolerate a
torn final record (crash mid-append). Controllers then resume by re-listing,
exactly the reference's posture — nothing outside spec/status survives.

Record encoding reuses the manifest codec (api/serialization.py) plus the
internal identity fields (uid/resourceVersion/creationTimestamp) that
to_dict deliberately omits from user-facing manifests; from_dict hydrates
them back because they are real ObjectMeta fields.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
from typing import Optional

from karpenter_tpu.api.serialization import (
    KINDS,
    from_dict,
    from_manifest,
    to_dict,
)
from karpenter_tpu.store.store import DELETED, Store, _key, _kind_of
from karpenter_tpu.utils.log import logger

log = logger()

_SNAPSHOT = "snapshot.json"
_WAL = "wal.jsonl"

# Kinds that live in the store but are not user-facing manifest kinds
# (the apiserver has these too — e.g. coordination.k8s.io Leases — and
# etcd persists them all the same).
_EXTRA_KINDS: dict = {}


def register_persistent_kind(kind: str, cls: type) -> None:
    _EXTRA_KINDS[kind] = cls


def _builtin_extra_kinds() -> None:
    from karpenter_tpu.leaderelection import Lease

    register_persistent_kind("Lease", Lease)


_builtin_extra_kinds()


def encode_object(obj) -> dict:
    """Manifest dict + internal identity, sufficient to reconstruct exactly."""
    doc = to_dict(obj)
    doc.setdefault("kind", _kind_of(obj))
    meta = doc.setdefault("metadata", {})
    meta["uid"] = obj.metadata.uid
    meta["resourceVersion"] = obj.metadata.resource_version
    meta["creationTimestamp"] = obj.metadata.creation_timestamp
    return doc


def decode_object(doc: dict):
    kind = doc.get("kind")
    if kind in KINDS:
        return from_manifest(doc)
    cls = _EXTRA_KINDS.get(kind)
    if cls is None:
        raise ValueError(f"unknown persisted kind {kind!r}")
    body = {k: v for k, v in doc.items() if k not in ("apiVersion", "kind")}
    return from_dict(cls, body)


class DurableStore(Store):
    """Store with etcd-grade durability on a local data directory.

    fsync=True fsyncs every WAL append (slow, survives power loss);
    fsync=False (default) flushes to the OS on every append (survives
    process crash, the failure mode that matters for a leader-elected
    control plane — a peer takes over on machine loss, reference:
    cmd/controller/main.go:58-59).
    """

    def __init__(
        self,
        data_dir: str,
        fsync: bool = False,
        compact_every: int = 4096,
    ):
        super().__init__()
        self.data_dir = data_dir
        self.fsync = fsync
        self.compact_every = max(1, int(compact_every))
        self._wal_count = 0
        self._wal_file = None
        self._wal_dirty = False  # an append failed; WAL has a gap
        self._io_lock = threading.Lock()
        os.makedirs(data_dir, exist_ok=True)
        # exclusive data-dir lock: two processes appending to one WAL would
        # interleave records and corrupt the journal (leader election does
        # NOT protect against this — each process's lease lives in its own
        # store); fail fast like etcd does on a locked member dir
        self._lock_file = open(os.path.join(data_dir, "LOCK"), "w")
        try:
            fcntl.flock(self._lock_file, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_file.close()
            raise RuntimeError(
                f"data dir {data_dir} is locked by another process"
            ) from None
        self._recovering = True
        try:
            self._recover()
        finally:
            self._recovering = False
        self._wal_file = open(self._wal_path, "a", encoding="utf-8")

    # -- paths -------------------------------------------------------------

    @property
    def _snapshot_path(self) -> str:
        return os.path.join(self.data_dir, _SNAPSHOT)

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.data_dir, _WAL)

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        restored = 0
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path, encoding="utf-8") as f:
                snap = json.load(f)
            self._rv = int(snap.get("rv", 0))
            for doc in snap.get("objects", []):
                self._restore(decode_object(doc))
                restored += 1
        replayed = self._replay_wal()
        if restored or replayed:
            log.info(
                "recovered %d objects (snapshot=%d, wal=%d) rv=%d from %s",
                len(self._objects), restored, replayed, self._rv, self.data_dir,
            )

    def _replay_wal(self) -> int:
        if not os.path.exists(self._wal_path):
            return 0
        replayed = 0
        valid_end = 0
        torn = False
        with open(self._wal_path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    valid_end += len(raw)
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    # torn final append from a crash — everything before it
                    # is intact because records are written atomically in
                    # rv order under the store lock
                    log.warning("wal: discarding torn record tail")
                    torn = True
                    break
                self._apply(record)
                replayed += 1
                valid_end += len(raw)
        if torn:
            # drop the fragment so the next append starts on a record
            # boundary rather than concatenating onto the torn line
            with open(self._wal_path, "rb+") as f:
                f.truncate(valid_end)
        else:
            # a crash can also persist a full valid record minus its
            # trailing newline; repair the boundary or the next append
            # would concatenate onto that line and a later recovery would
            # discard BOTH acknowledged records as one torn tail
            with open(self._wal_path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
        self._wal_count = replayed
        return replayed

    def _apply(self, record: dict) -> None:
        event = record["event"]
        obj = decode_object(record["object"])
        key = (
            record["object"]["kind"],
            obj.metadata.namespace,
            obj.metadata.name,
        )
        if event == DELETED:
            stored = self._objects.pop(key, None)
            if stored is not None:
                self._index_remove(stored)
        else:
            self._restore(obj)
        if isinstance(obj.metadata.resource_version, int):
            self._rv = max(self._rv, obj.metadata.resource_version)

    def _restore(self, obj) -> None:
        key = _key(obj)
        stored = self._objects.get(key)
        if stored is not None:
            self._index_remove(stored)
        self._objects[key] = obj
        self._index_add(obj)
        if isinstance(obj.metadata.resource_version, int):
            self._rv = max(self._rv, obj.metadata.resource_version)

    # -- write-time restorability gate -------------------------------------

    def _check_restorable(self, obj) -> None:
        """Fail at WRITE time if this kind could not be decoded at
        recovery: journaling an unregistered custom kind (easy to do —
        the scale subresource duck-types any spec.replicas object)
        would otherwise succeed silently and crash the NEXT process
        start inside _recover, far from the mistake."""
        kind = _kind_of(obj)
        if kind not in KINDS and kind not in _EXTRA_KINDS:
            raise ValueError(
                f"kind {kind!r} cannot be journaled durably: recovery "
                "could not decode it. Call store.persistence."
                f"register_persistent_kind({kind!r}, "
                f"{type(obj).__name__}) before storing it in a durable "
                "store."
            )

    def create(self, obj):
        self._check_restorable(obj)
        return super().create(obj)

    def update(self, obj):
        self._check_restorable(obj)
        return super().update(obj)

    def apply_event(self, event: str, obj) -> None:
        # every journaling entry path is gated, DELETED included: a
        # delete record of an unknown kind is decoded at recovery too
        self._check_restorable(obj)
        super().apply_event(event, obj)

    # -- journaling --------------------------------------------------------

    def _notify(self, event: str, obj) -> None:
        # called under the store lock at every mutation, with the stored
        # (post-mutation) object — journal BEFORE watchers observe, so a
        # crash between the two replays a superset of what watchers saw
        if not self._recovering:
            self._append({"event": event, "object": encode_object(obj)})
        super()._notify(event, obj)

    def _append(self, record: dict) -> None:
        """Journal one record. Never raises: memory is authoritative and
        watchers must observe exactly what memory holds, so an I/O failure
        degrades durability (loudly) instead of leaving the caller with a
        mutation that is half-acknowledged. A failed append leaves a gap in
        the WAL, so the store marks itself dirty and self-heals by writing
        a FULL snapshot (which supersedes the gappy WAL) as soon as I/O
        succeeds again."""
        with self._io_lock:
            try:
                if self._wal_dirty:
                    self._compact_locked()  # snapshot == full current state
                    self._wal_dirty = False
                    log.warning("wal: journal healed via full snapshot")
                    return
                self._wal_file.write(
                    json.dumps(record, sort_keys=True) + "\n"
                )
                self._wal_file.flush()
                if self.fsync:
                    os.fsync(self._wal_file.fileno())
                self._wal_count += 1
                if self._wal_count >= self.compact_every:
                    self._compact_locked()
            except OSError:
                self._wal_dirty = True
                log.exception(
                    "wal: append failed — durability degraded until the "
                    "next successful snapshot"
                )

    def _compact_locked(self) -> None:
        """Write a full snapshot atomically, then truncate the WAL.
        Caller holds _io_lock; the store lock is already held by the
        mutating caller, so the object map is consistent."""
        snap = {
            "rv": self._rv,
            "objects": [encode_object(o) for o in self._objects.values()],
        }
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        # make the rename durable BEFORE truncating the WAL: if power is
        # lost with the truncation on disk but the rename not, recovery
        # would pair the OLD snapshot with an empty WAL and lose every
        # record since the previous compaction
        dir_fd = os.open(self.data_dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._wal_file.close()
        self._wal_file = open(self._wal_path, "w", encoding="utf-8")
        if self.fsync:
            os.fsync(self._wal_file.fileno())
        self._wal_count = 0

    def compact(self) -> None:
        """Force a snapshot + WAL truncation (tests, graceful shutdown)."""
        with self._lock, self._io_lock:
            self._compact_locked()

    def close(self) -> None:
        with self._io_lock:
            if self._wal_file is not None and not self._wal_file.closed:
                self._wal_file.flush()
                self._wal_file.close()
            if not self._lock_file.closed:
                self._lock_file.close()  # releases the flock


def open_store(data_dir: Optional[str], **kwargs) -> Store:
    """Factory: durable when a data dir is configured, in-memory otherwise."""
    if data_dir:
        return DurableStore(data_dir, **kwargs)
    return Store()
