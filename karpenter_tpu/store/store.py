"""In-memory watchable object store — the coordination bus.

The reference's controllers never talk to each other in memory: all
cross-controller communication rides kube-apiserver CRD spec/status and the
scale subresource (reference: SURVEY.md §2.2; pkg/autoscaler/autoscaler.go:196-221).
This store is the TPU build's equivalent bus: namespaced objects keyed by
(kind, namespace, name) with resourceVersions, deep-copy isolation on every
read/write (nothing shares mutable state through the store), watch callbacks,
a pod spec.nodeName index (reference: pkg/controllers/manager.go:73-79), and
a pluggable scale subresource so any HorizontalAutoscaler can target any
registered scalable kind (reference: scalablenodegroup.go:51).

Copy discipline (the hottest host path at fleet scale): objects are cloned
with utils/clone.fast_clone on every intake and every read-out, and the
store is COPY-ON-WRITE internally — no stored object is ever mutated after
insertion (patch_status/update_scale replace the stored instance). That
lets watch callbacks receive the stored instance itself with NO copy; the
documented watcher contract (read-only) is what makes a 1%-churn tick over
100k pods affordable.

Durability mirrors the reference's checkpoint/resume story (SURVEY.md §5):
ALL durable state lives in object spec/status here; controllers and the
device solver are stateless and resume by re-listing.
"""

from __future__ import annotations

import threading

from karpenter_tpu.utils.clone import fast_clone
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

ADDED = "Added"
MODIFIED = "Modified"
DELETED = "Deleted"


class NotFoundError(KeyError):
    pass


class ConflictError(RuntimeError):
    pass


@dataclass
class Scale:
    """The scale-subresource view (k8s autoscaling/v1 Scale analog)."""

    namespace: str
    name: str
    spec_replicas: Optional[int]
    status_replicas: int


@dataclass
class _ScaleHooks:
    get_spec: Callable
    set_spec: Callable
    get_status: Callable


_scale_kinds: Dict[str, _ScaleHooks] = {}


def register_scale_kind(kind: str, get_spec, set_spec, get_status) -> None:
    """Register a kind as implementing the scale subresource."""
    _scale_kinds[kind] = _ScaleHooks(get_spec, set_spec, get_status)


def _kind_of(obj) -> str:
    return getattr(obj, "KIND", type(obj).__name__)


def _key(obj) -> Tuple[str, str, str]:
    return (_kind_of(obj), obj.metadata.namespace, obj.metadata.name)


class Store:
    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._rv = 0
        self._watchers: List[Tuple[Optional[str], Callable]] = []
        # spec.nodeName index for Pods
        self._pods_by_node: Dict[str, set] = {}
        # kind -> insertion-ordered keys: list(kind)/keys(kind) must
        # never scan OTHER kinds (listing zero Namespaces used to walk
        # all 100k pods); dict-as-ordered-set keeps the iteration order
        # callers observed before the index existed
        self._by_kind: Dict[str, Dict[Tuple[str, str, str], None]] = {}

    # -- watch ------------------------------------------------------------

    def watch(self, kind: Optional[str], callback: Callable) -> None:
        """Subscribe to mutation events. kind=None watches everything.
        callback(event_type, obj) is invoked synchronously with the STORED
        object itself (zero copies: the store is copy-on-write, so the
        instance can never change after delivery) — treat it as strictly
        read-only."""
        with self._lock:
            self._watchers.append((kind, callback))

    def _notify(self, event: str, obj) -> None:
        # obj is the stored (immutable-after-insert) instance: no copy
        kind = _kind_of(obj)
        for want_kind, callback in list(self._watchers):
            if want_kind is None or want_kind == kind:
                callback(event, obj)

    # -- index maintenance ------------------------------------------------

    def _index_add(self, obj) -> None:
        self._by_kind.setdefault(_kind_of(obj), {})[_key(obj)] = None
        if _kind_of(obj) == "Pod" and obj.spec.node_name:
            self._pods_by_node.setdefault(obj.spec.node_name, set()).add(_key(obj))

    def _index_remove(self, obj) -> None:
        kind_keys = self._by_kind.get(_kind_of(obj))
        if kind_keys is not None:
            kind_keys.pop(_key(obj), None)
            if not kind_keys:
                del self._by_kind[_kind_of(obj)]
        self._node_index_remove(obj)

    def _node_index_remove(self, obj) -> None:
        if _kind_of(obj) == "Pod" and obj.spec.node_name:
            keys = self._pods_by_node.get(obj.spec.node_name)
            if keys is not None:
                keys.discard(_key(obj))
                if not keys:
                    del self._pods_by_node[obj.spec.node_name]

    def _index_replace(self, old, new) -> None:
        """Same-key replacement (update / watch echo): the kind index
        keeps the key's POSITION — remove-then-add would move every
        modified object to the end, churning list() order (and with it
        the oracle encoder's row order) on every status write. Only the
        nodeName index re-files (the binding may have changed)."""
        self._node_index_remove(old)
        if _kind_of(new) == "Pod" and new.spec.node_name:
            self._pods_by_node.setdefault(
                new.spec.node_name, set()
            ).add(_key(new))

    # -- CRUD -------------------------------------------------------------

    def create(self, obj):
        """Persist a copy of obj. Like controller-runtime's Create, the
        CALLER's object is stamped in place with the minted identity
        (uid, creationTimestamp, resourceVersion) and returned — one
        clone per create, on the watch-fan-out hot path."""
        with self._lock:
            key = _key(obj)
            if key in self._objects:
                raise ConflictError(f"{key} already exists")
            # ALWAYS mint a fresh incarnation (apiserver semantics: the
            # server assigns uid/creationTimestamp on create, whatever the
            # request carried) — a caller re-creating with an object from a
            # previous incarnation must not resurrect its uid. Recovered
            # objects keep theirs via the WAL restore path, never create().
            obj.metadata.uid = ""
            obj.metadata.creation_timestamp = 0.0
            obj.metadata.ensure_identity()
            self._rv += 1
            obj.metadata.resource_version = self._rv
            stored = fast_clone(obj)
            self._objects[key] = stored
            self._index_add(stored)
            self._notify(ADDED, stored)
            return obj

    def get(self, kind: str, namespace: str, name: str):
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return fast_clone(obj)

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def update(self, obj):
        """Replace spec+metadata+status wholesale (like an apiserver UPDATE).
        Optimistic concurrency: a stale resource_version is rejected so a
        slow writer cannot silently clobber a concurrent change (e.g. the
        autoscaler's scale write)."""
        with self._lock:
            key = _key(obj)
            stored = self._objects.get(key)
            if stored is None:
                raise NotFoundError(f"{key} not found")
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version != stored.metadata.resource_version
            ):
                raise ConflictError(
                    f"{key}: stale resourceVersion "
                    f"{obj.metadata.resource_version} != "
                    f"{stored.metadata.resource_version}"
                )
            self._rv += 1
            obj.metadata.resource_version = self._rv
            obj.metadata.uid = stored.metadata.uid
            obj.metadata.creation_timestamp = stored.metadata.creation_timestamp
            new = fast_clone(obj)
            self._objects[key] = new
            self._index_replace(stored, new)
            self._notify(MODIFIED, new)
            return obj

    def patch_status(self, obj):
        """Merge-patch ONLY the status subtree onto the stored object,
        mirroring the reference's Status().Patch(MergeFrom(persisted))
        (reference: pkg/controllers/controller.go:93) — concurrent spec
        writes are never clobbered by a status update."""
        # injection point (faults/registry.py): a failed status write is
        # the apiserver-conflict/outage analog; the engine requeues the
        # reconcile with backoff instead of crashing the tick
        from karpenter_tpu.faults import inject

        inject("store.patch_status")
        with self._lock:
            key = _key(obj)
            stored = self._objects.get(key)
            if stored is None:
                raise NotFoundError(f"{key} not found")
            # copy-on-write: watchers hold references to the previous
            # instance, which must never change after delivery
            new = fast_clone(stored)
            new.status = fast_clone(obj.status)
            self._rv += 1
            new.metadata.resource_version = self._rv
            self._objects[key] = new
            self._notify(MODIFIED, new)
            return fast_clone(new)

    def delete(self, obj_or_kind, namespace: Optional[str] = None, name=None):
        with self._lock:
            if isinstance(obj_or_kind, str):
                key = (obj_or_kind, namespace, name)
            else:
                key = _key(obj_or_kind)
            stored = self._objects.pop(key, None)
            if stored is None:
                raise NotFoundError(f"{key} not found")
            self._index_remove(stored)
            self._notify(DELETED, stored)

    def keys(self, kind: str) -> list:
        """(kind, namespace, name) keys of a kind, without copying objects."""
        with self._lock:
            return list(self._by_kind.get(kind, ()))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> list:
        with self._lock:
            out = []
            for key in self._by_kind.get(kind, ()):
                obj = self._objects[key]
                if namespace is not None and key[1] != namespace:
                    continue
                if label_selector is not None and not all(
                    obj.metadata.labels.get(lk) == lv
                    for lk, lv in label_selector.items()
                ):
                    continue
                out.append(fast_clone(obj))
            return out

    def pods_on_node(self, node_name: str) -> list:
        """Pods indexed by spec.nodeName (reference: manager.go:54-55,73-79)."""
        with self._lock:
            return [
                fast_clone(self._objects[key])
                for key in sorted(self._pods_by_node.get(node_name, set()))
                if key in self._objects
            ]

    def apply_event(self, event: str, obj) -> None:
        """Apply an event from an EXTERNAL source of truth (an apiserver
        watch stream) verbatim: no identity minting, no resourceVersion
        bump or conflict check — the upstream's metadata IS the truth.
        Watchers observe it exactly like a local mutation."""
        with self._lock:
            key = _key(obj)
            stored = self._objects.get(key)
            if event == DELETED:
                if stored is None:
                    return
                del self._objects[key]
                self._index_remove(stored)
                self._notify(DELETED, stored)
                return
            if (
                stored is not None
                and stored.metadata.resource_version
                == obj.metadata.resource_version
            ):
                return  # relist echo of an unchanged object: no watcher spam
            obj = fast_clone(obj)
            self._objects[key] = obj
            if stored is not None:
                self._index_replace(stored, obj)
            else:
                self._index_add(obj)
            if isinstance(obj.metadata.resource_version, int):
                # externally-sourced rvs may be opaque non-numeric strings
                # (k8s API conventions); only numeric ones can advance the
                # local minting counter, and equality above never needs more
                self._rv = max(self._rv, obj.metadata.resource_version)
            self._notify(MODIFIED if stored is not None else ADDED, obj)

    # -- scale subresource -------------------------------------------------

    def _scale_hooks(self, kind: str, obj) -> _ScaleHooks:
        """Registered hooks, else the duck-typed fallback: any stored
        object shaped like a scalable workload (spec.replicas +
        status.replicas — Deployments, StatefulSets, and every
        kubebuilder scale-marker CRD use exactly this layout) implements
        scale without registration. The reference gets the same
        generality from discovery + scale.ScalesGetter
        (reference: autoscaler.go:196-237); in-memory mode derives it
        from the object shape."""
        hooks = _scale_kinds.get(kind)
        if hooks is not None:
            return hooks
        spec = getattr(obj, "spec", None)
        status = getattr(obj, "status", None)
        if hasattr(spec, "replicas") and hasattr(status, "replicas"):
            return _DUCK_SCALE_HOOKS
        raise NotFoundError(f"kind {kind} does not implement scale")

    def get_scale(
        self, kind: str, namespace: str, name: str, api_version: str = ""
    ) -> Scale:
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            hooks = self._scale_hooks(kind, obj)
            status = hooks.get_status(obj)
            return Scale(
                namespace=namespace,
                name=name,
                spec_replicas=hooks.get_spec(obj),
                status_replicas=int(status) if status is not None else 0,
            )

    def update_scale(
        self, kind: str, scale: Scale, api_version: str = ""
    ) -> None:
        with self._lock:
            obj = self._objects.get((kind, scale.namespace, scale.name))
            if obj is None:
                raise NotFoundError(
                    f"{kind} {scale.namespace}/{scale.name} not found"
                )
            hooks = self._scale_hooks(kind, obj)
            # copy-on-write (same contract as patch_status)
            new = fast_clone(obj)
            hooks.set_spec(new, scale.spec_replicas)
            self._rv += 1
            new.metadata.resource_version = self._rv
            self._objects[(kind, scale.namespace, scale.name)] = new
            self._notify(MODIFIED, new)


_DUCK_SCALE_HOOKS = _ScaleHooks(
    get_spec=lambda obj: obj.spec.replicas,
    set_spec=lambda obj, replicas: setattr(obj.spec, "replicas", replicas),
    get_status=lambda obj: obj.status.replicas,
)


def _register_builtin_scale_kinds():
    """ScalableNodeGroup implements scale at .spec.replicas/.status.replicas
    (reference: scalablenodegroup.go:51 kubebuilder scale marker)."""

    def get_spec(sng):
        return sng.spec.replicas

    def set_spec(sng, replicas):
        sng.spec.replicas = replicas

    def get_status(sng):
        return sng.status.replicas

    register_scale_kind("ScalableNodeGroup", get_spec, set_spec, get_status)


_register_builtin_scale_kinds()
