"""Priority- and preemption-aware packing (docs/preemption.md).

Answers "what do I evict to place this?" fleet-wide in one device
dispatch: the planner encodes candidates/nodes/victims
(preemption/planner.py), the batched eviction kernel solves every
candidate at once (ops/preempt.py via SolverService.preempt), and the
engine applies budgets, conflict resolution, consolidation
coordination, and eviction actuation (preemption/engine.py).
"""

from karpenter_tpu.preemption.engine import (
    PreemptionConfig,
    PreemptionEngine,
)
from karpenter_tpu.preemption.planner import build_problem, plan_rows

__all__ = [
    "PreemptionConfig",
    "PreemptionEngine",
    "build_problem",
    "plan_rows",
]
