"""Preemption planner: encode the fleet's eviction-planning problem.

The consolidation planner (consolidation/planner.py) asks "can this
node's pods re-pack elsewhere?"; this planner asks the dual question —
"which occupancy do I evict to place THIS pending pod?" — and encodes it
for the batched eviction kernel (ops/preempt.py) in one PreemptInputs:

  * the NODE axis is the cluster's nodes (one column per node,
    reusing the consolidation ClusterView's free-capacity accounting:
    allocatable minus scheduler-effective bound requests);
  * the CANDIDATE axis is the high-priority pending pods, with
    per-(candidate, node) feasibility — nodeSelector, required node
    affinity, untolerated hard taints, not-ready/cordoned receivers,
    coordination holds — folded host-side into pod_node_forbidden
    (the same fold consolidation does, at the same KB scale);
  * the VICTIM axis is the bound occupancy, sorted by (node, priority,
    name) — the kernel's sorted-victim contract — with the policy mask
    (do-not-disrupt pods/nodes, held nodes) in victim_evictable;
  * node_tier marks preemptible/spot capacity: the capacity-type node
    labels (api/core.capacity_tier_of) OR an owning ScalableNodeGroup
    with spec.preemptible — victims there are evictable-by-contract
    regardless of priority (the spot-reclaim model).

The kernel plans candidates independently; conflict resolution (two
plans claiming one victim), budgets, and actuation live in engine.py.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import (
    Taint,
    capacity_tier_of,
    effective_priority,
)
from karpenter_tpu.consolidation.planner import (
    ClusterView,
    _pod_compatible,
    _opted_out,
    request_row,
    resource_universe_for,
)
from karpenter_tpu.ops.preempt import MAX_VICTIMS, PreemptInputs


def _resource_universe(view: ClusterView, candidates: List) -> List[str]:
    """The preemption universe: node free capacity + EVERY bound pod
    (the victims) + the pending candidates — the shared
    consolidation-planner rule over this planner's pod set."""
    import itertools

    return resource_universe_for(
        view,
        itertools.chain(
            (pod for nv in view.nodes for pod in nv.pods), candidates
        ),
    )


def _victim_axis(
    view: ClusterView,
    resources: List[str],
    default_priority: int,
    excluded: FrozenSet[str],
    max_victims: int,
):
    """(requests, priority, node, evictable, keys): the bound occupancy
    sorted by (node column, priority, name) — the kernel's contract.
    Overflow past max_victims drops the HIGHEST-priority victims first
    (the least evictable ones — strictly conservative: dropping a
    victim only removes eviction options, never invents them)."""
    rows = []  # (node_col, priority, name_key, pod, evictable)
    for col, nv in enumerate(view.nodes):
        node_blocked = (
            nv.name in excluded or _opted_out(nv.node)
        )
        for pod in nv.pods:
            rows.append(
                (
                    col,
                    effective_priority(pod, default=default_priority),
                    (pod.metadata.namespace, pod.metadata.name),
                    pod,
                    not node_blocked and not _opted_out(pod),
                )
            )
    if len(rows) > max_victims:
        rows = sorted(rows, key=lambda r: (r[1], r[0], r[2]))[:max_victims]
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    v = len(rows)
    requests = np.zeros((v, len(resources)), np.float32)
    priority = np.zeros(v, np.int32)
    node = np.zeros(v, np.int32)
    evictable = np.zeros(v, bool)
    keys: List[Tuple[str, str]] = []
    for i, (col, prio, key, pod, ok) in enumerate(rows):
        requests[i] = request_row(pod, resources)
        priority[i] = prio
        node[i] = col
        evictable[i] = ok
        keys.append(key)
    return requests, priority, node, evictable, keys


def _tier_of(nv, preemptible_groups) -> int:
    """1 = preemptible/spot: the capacity-type node labels OR a
    spec.preemptible owning group."""
    if capacity_tier_of(nv.node.metadata.labels) > 0:
        return 1
    if (
        nv.group is not None
        and (nv.group[0], nv.group[2]) in preemptible_groups
    ):
        return 1
    return 0


def _node_axis(
    view: ClusterView, candidates, resources, excluded_nodes,
    preemptible_groups,
):
    """(node_free, node_tier, forbidden): the shared node-column
    operands — free capacity, capacity tier (spot labels OR a
    spec.preemptible owner), and the host-folded per-(candidate, node)
    feasibility mask (selectors/affinity/taints, non-receivers,
    coordination holds)."""
    n, c = len(view.nodes), len(candidates)
    node_free = np.zeros((n, len(resources)), np.float32)
    node_tier = np.zeros(n, np.int32)
    forbidden = np.zeros((c, n), bool)
    for col, nv in enumerate(view.nodes):
        for r, resource in enumerate(resources):
            node_free[col, r] = nv.free.get(resource, 0.0)
        node_tier[col] = _tier_of(nv, preemptible_groups)
        if not nv.receiver or nv.name in excluded_nodes:
            forbidden[:, col] = True
            continue
        labels = dict(nv.node.metadata.labels)
        hard_taints = [
            Taint(key=t.key, value=t.value, effect=t.effect)
            for t in nv.node.spec.taints
            if t.effect in ("NoSchedule", "NoExecute")
        ]
        for i, pod in enumerate(candidates):
            if not _pod_compatible(pod, labels, hard_taints):
                forbidden[i, col] = True
    return node_free, node_tier, forbidden


def build_problem(
    view: ClusterView,
    candidates: List,
    default_priority: int = 0,
    excluded_nodes: FrozenSet[str] = frozenset(),
    preemptible_groups: FrozenSet[Tuple[str, str]] = frozenset(),
    max_victims: int = MAX_VICTIMS,
) -> Tuple[PreemptInputs, List[Tuple[str, str]], List[str]]:
    """(inputs, victim_keys, node_names) for the given candidate pods.

    `excluded_nodes` are coordination holds — nodes the consolidation
    FSM (or a previous preemption round) currently owns: their columns
    are forbidden receivers AND their pods non-evictable, so the two
    disruption engines can never fight over one node.
    `preemptible_groups` are (namespace, nodeGroupRef) pairs whose
    ScalableNodeGroup declares spec.preemptible."""
    resources = _resource_universe(view, candidates)
    c = len(candidates)
    node_free, node_tier, forbidden = _node_axis(
        view, candidates, resources, excluded_nodes, preemptible_groups
    )

    pod_requests = np.zeros((c, len(resources)), np.float32)
    pod_priority = np.zeros(c, np.int32)
    for i, pod in enumerate(candidates):
        pod_requests[i] = request_row(pod, resources)
        pod_priority[i] = effective_priority(
            pod, default=default_priority
        )

    vreq, vprio, vnode, vevict, victim_keys = _victim_axis(
        view, resources, default_priority, excluded_nodes, max_victims
    )
    inputs = PreemptInputs(
        pod_requests=pod_requests,
        pod_priority=pod_priority,
        pod_valid=np.ones(c, bool),
        pod_node_forbidden=forbidden,
        node_free=node_free,
        node_tier=node_tier,
        victim_requests=vreq,
        victim_priority=vprio,
        victim_node=vnode,
        victim_valid=np.ones(len(victim_keys), bool),
        victim_evictable=vevict,
    )
    return inputs, victim_keys, [nv.name for nv in view.nodes]


def plan_rows(out, victim_keys: List[Tuple[str, str]], node_names: List[str]) -> List[Optional[Dict]]:
    """Decode PreemptOutputs into per-candidate plan dicts:
    {"node": name, "evictions": [(ns, name), ...]} — None for
    unplaceable candidates. Zero-eviction plans come back with an empty
    eviction list (the pod fits already; nothing to actuate)."""
    chosen = np.asarray(out.chosen_node)
    mask = np.asarray(out.evict_mask)
    plans: List[Optional[Dict]] = []
    for i in range(chosen.shape[0]):
        col = int(chosen[i])
        if col < 0:
            plans.append(None)
            continue
        plans.append(
            {
                "node": node_names[col],
                "evictions": [
                    victim_keys[v] for v in np.nonzero(mask[i])[0]
                ],
            }
        )
    return plans
