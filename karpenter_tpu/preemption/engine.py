"""Preemption engine: budgets, conflict resolution, eviction actuation.

The planner (planner.py) and kernel (ops/preempt.py) answer the pure
question — for each high-priority pending pod, the cheapest eviction
set that admits it. This module wraps those plans in the operational
safety a production eviction needs, deliberately mirroring the
consolidation engine's posture (consolidation/engine.py) so the two
disruption subsystems behave — and coordinate — alike:

  * DO-NOT-DISRUPT: pods (or nodes) annotated
    `karpenter.sh/do-not-disrupt: "true"` are never victims — folded
    into the kernel's evictable mask by the planner.
  * NODE COORDINATION: nodes the consolidation FSM currently owns
    (cordoned / verifying / draining) are excluded from preemption —
    forbidden as receivers AND protected as victims — and nodes a
    preemption plan just targeted are HELD for `hold_s`, which the
    consolidation engine's candidate gate consults (its `node_guard`
    seam). The two engines can never disrupt one node at once.
  * DISRUPTION BUDGETS (PDB-style): at most `budget_per_group`
    evictions may be charged against one ScalableNodeGroup's nodes
    inside a hold window — per-group override via
    spec.eviction_budget. Plans that would exceed the budget are
    DEFERRED to a later round, not trimmed (a partial eviction set
    frees capacity without admitting the candidate — pure disruption).
  * CONFLICT RESOLUTION: the kernel plans candidates independently;
    the engine accepts plans greedily in candidate order (highest
    priority first — the planner sorts them) and defers any plan whose
    victims or target node a previously-accepted plan already claimed.
  * NO DUPLICATE EVICTIONS: a victim is evicted at most once — claimed
    victims are tracked per round, and an eviction is a conditional
    store delete (already-gone pods are counted as no-ops, never
    retried as fresh disruptions).

Actuation is API-level eviction: the victim Pod is deleted through the
store (the in-process analog of the Eviction subresource); its workload
controller re-creates it as a pending pod, which the ordinary
pending-capacity solve then routes to a scale-up — exactly how
kube-scheduler preemption composes with cluster autoscaling.

Metrics (subsystem "preemption", runtime registry):
karpenter_preemption_{candidates_evaluated_total,plans_total,
evictions_total,deferred_total,unplaceable,batch_eval_ms}.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api.core import effective_priority
from karpenter_tpu.consolidation.planner import (
    cluster_view,
    discover_groups,
)
from karpenter_tpu.faults import inject
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.preemption import planner as P
from karpenter_tpu.store.columnar import is_pending
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "preemption"

CANDIDATES_EVALUATED = "candidates_evaluated_total"
PLANS = "plans_total"
EVICTIONS = "evictions_total"
DEFERRED = "deferred_total"
UNPLACEABLE = "unplaceable"
BATCH_EVAL_MS = "batch_eval_ms"


@dataclass
class PreemptionConfig:
    plan_interval_s: float = 30.0
    # default max evictions charged against one ScalableNodeGroup's
    # nodes per hold window (spec.eviction_budget overrides per group)
    budget_per_group: int = 1
    # pending pods below this priority never trigger evictions (they
    # wait for ordinary scale-up); 1 keeps the default-priority fleet
    # (priority 0) preemption-free
    min_candidate_priority: int = 1
    max_candidates: int = 64
    max_victims: int = 4096
    # fleet default for pods naming an unknown PriorityClass
    default_priority: int = 0
    # how long an accepted plan's target node stays held (guards
    # consolidation away, and spaces repeat disruption of one node)
    hold_s: float = 120.0
    backend: Optional[str] = None  # None = the service's default


@dataclass
class _Charge:
    """One accepted plan's budget charge against a group."""

    expires: float
    evictions: int = 1


class PreemptionEngine:
    """Owns the plan cadence, budgets, holds, and eviction actuation."""

    def __init__(
        self,
        store,
        solver_service,
        consolidation=None,
        registry: Optional[GaugeRegistry] = None,
        config: Optional[PreemptionConfig] = None,
        clock=None,
    ):
        self.store = store
        self.service = solver_service
        self.consolidation = consolidation
        # crash safety (karpenter_tpu/recovery, docs/resilience.md):
        # holds and budget charges journal through `journal` so a
        # restarted controller keeps honoring disruption budgets spent
        # before the crash; `disruption_gate` is the recovery warm-up —
        # no eviction planning while it returns False
        self.journal = None
        self.disruption_gate = None
        self.config = config or PreemptionConfig()
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self.clock = clock or _time.monotonic
        self._last_plan: Optional[float] = None
        # node name -> hold expiry (the consolidation node_guard reads
        # this through active_nodes())
        self._holds: Dict[str, float] = {}
        # candidate (namespace, name) -> hold expiry: a candidate whose
        # plan was ACTUATED is not re-planned until the scheduler has
        # had hold_s to bind it onto the freed capacity — without this,
        # a still-pending candidate would trigger fresh evictions on
        # another node every round (disruption amplification)
        self._candidate_holds: Dict[Tuple[str, str], float] = {}
        # budget key (namespace, nodeGroupRef) -> live charges
        self._charges: Dict[Tuple[str, str], List[_Charge]] = {}
        reg = self.registry.register
        self._c_evaluated = reg(
            SUBSYSTEM, CANDIDATES_EVALUATED, kind="counter"
        )
        self._c_plans = reg(SUBSYSTEM, PLANS, kind="counter")
        self._c_evictions = reg(SUBSYSTEM, EVICTIONS, kind="counter")
        self._c_deferred = reg(SUBSYSTEM, DEFERRED, kind="counter")
        self._g_unplaceable = reg(SUBSYSTEM, UNPLACEABLE)
        self._g_eval_ms = reg(SUBSYSTEM, BATCH_EVAL_MS)

    # -- coordination surface ---------------------------------------------

    def active_nodes(self) -> Set[str]:
        """Nodes currently held by an accepted eviction plan — the
        consolidation engine's node_guard seam consults this so a node
        being preempted onto is never simultaneously drained."""
        now = self.clock()
        self._holds = {
            n: exp for n, exp in self._holds.items() if exp > now
        }
        return set(self._holds)

    def _excluded_nodes(self) -> Set[str]:
        excluded = self.active_nodes()
        if self.consolidation is not None:
            excluded |= set(self.consolidation.in_flight())
        return excluded

    # -- plan cadence ------------------------------------------------------

    def maybe_plan(self, now: Optional[float] = None) -> None:
        """Plan at most once per plan_interval_s; the ScalableNodeGroup
        controller calls this every reconcile like consolidation's."""
        now = self.clock() if now is None else now
        if (
            self._last_plan is not None
            and now - self._last_plan < self.config.plan_interval_s
        ):
            return
        self.plan(now)

    def _candidates(self) -> List:
        """High-priority pending pods, highest priority first (the
        greedy acceptance order), capped at max_candidates."""
        default = self.config.default_priority
        now = self.clock()
        self._candidate_holds = {
            k: exp
            for k, exp in self._candidate_holds.items()
            if exp > now
        }
        pending = [
            pod
            for pod in self.store.list("Pod")
            if is_pending(pod)
            and (pod.metadata.namespace, pod.metadata.name)
            not in self._candidate_holds
            and effective_priority(pod, default=default)
            >= self.config.min_candidate_priority
        ]
        pending.sort(
            key=lambda p: (
                -effective_priority(p, default=default),
                p.metadata.namespace,
                p.metadata.name,
            )
        )
        return pending[: self.config.max_candidates]

    def _preemptible_groups(self) -> frozenset:
        return frozenset(
            (sng.metadata.namespace, sng.metadata.name)
            for sng in self.store.list("ScalableNodeGroup")
            if sng.spec.preemptible
        )

    def plan(self, now: Optional[float] = None) -> Dict[tuple, Optional[dict]]:
        """One full round: snapshot, one batched eviction solve through
        the service, greedy conflict/budget resolution, actuation.
        Returns {(namespace, name): accepted plan or None} per candidate
        for observability/tests."""
        now = self.clock() if now is None else now
        if self.disruption_gate is not None and not self.disruption_gate():
            # recovery warm-up: no eviction until fleet state is
            # confirmed; _last_plan stays unset so the first
            # post-warm-up reconcile plans immediately
            return {}
        self._last_plan = now
        self._expire_charges(now)
        candidates = self._candidates()
        if not candidates:
            self._g_unplaceable.set("-", "-", 0.0)
            return {}
        groups = discover_groups(self.store)
        view = cluster_view(self.store, groups)
        inputs, victim_keys, node_names = P.build_problem(
            view,
            candidates,
            default_priority=self.config.default_priority,
            excluded_nodes=frozenset(self._excluded_nodes()),
            preemptible_groups=self._preemptible_groups(),
            max_victims=self.config.max_victims,
        )
        t0 = _time.perf_counter()
        out = self.service.preempt(inputs, backend=self.config.backend)
        self._g_eval_ms.set(
            "-", "-", (_time.perf_counter() - t0) * 1e3
        )
        self._c_evaluated.inc("-", "-", float(len(candidates)))
        self._g_unplaceable.set("-", "-", float(int(out.unplaceable)))
        plans = P.plan_rows(out, victim_keys, node_names)
        return self._resolve_and_actuate(
            view, candidates, plans, now
        )

    # -- resolution + actuation -------------------------------------------

    def _expire_charges(self, now: float) -> None:
        for key in list(self._charges):
            live = [
                c for c in self._charges[key] if c.expires > now
            ]
            if live:
                self._charges[key] = live
            else:
                del self._charges[key]

    @staticmethod
    def _budget_key(group: Optional[tuple], node: str) -> Tuple[str, str]:
        """Charges bind to the actuation target (namespace, ref); a
        node outside any actuatable group charges its OWN key — one
        ungrouped node's evictions must not throttle every other
        ungrouped node cluster-wide."""
        if group is not None and group[2]:
            return (group[0], group[2])
        return ("__node__", node)

    def _budget_left(self, group: Optional[tuple], node: str) -> int:
        """Remaining eviction budget for the target node's owner:
        spec.eviction_budget when set, else the engine default, minus
        live charges. Ungrouped nodes get the engine default (there is
        no spec to consult)."""
        budget = self.config.budget_per_group
        key = self._budget_key(group, node)
        if group is not None and group[2]:
            sng = self.store.try_get(
                "ScalableNodeGroup", group[0], group[2]
            )
            if sng is not None and sng.spec.eviction_budget is not None:
                budget = sng.spec.eviction_budget
        charged = sum(
            c.evictions for c in self._charges.get(key, [])
        )
        return budget - charged

    def _charge(
        self, group: Optional[tuple], count: int, now: float, node: str
    ) -> None:
        bkey = self._budget_key(group, node)
        self._charges.setdefault(bkey, []).append(
            _Charge(expires=now + self.config.hold_s, evictions=count)
        )
        self._journal_charges(bkey)

    # -- crash-safe journal (karpenter_tpu/recovery) -----------------------

    def _journal_charges(self, bkey: Tuple[str, str]) -> None:
        if self.journal is None:
            return
        live = self._charges.get(bkey, [])
        if live:
            self.journal.set(
                ("charge",) + bkey,
                [[c.expires, c.evictions] for c in live],
            )
        else:
            self.journal.delete(("charge",) + bkey)

    def _journal_hold(self, node: str, expires: Optional[float]) -> None:
        if self.journal is None:
            return
        if expires is None:
            self.journal.delete(("hold", node))
        else:
            self.journal.set(("hold", node), expires)

    def _journal_candidate_hold(self, key: Tuple[str, str]) -> None:
        if self.journal is not None:
            self.journal.set(
                ("cand",) + key, self._candidate_holds[key]
            )

    def snapshot_state(self) -> Dict[str, object]:
        """Full holds/charges table for the recovery checkpoint (the
        layout the journal folds to)."""
        from karpenter_tpu.recovery.journal import key_str

        state: Dict[str, object] = {}
        for node, exp in self._holds.items():
            state[key_str(("hold", node))] = exp
        for ckey, exp in self._candidate_holds.items():
            state[key_str(("cand",) + ckey)] = exp
        for bkey, charges in self._charges.items():
            if charges:
                state[key_str(("charge",) + bkey)] = [
                    [c.expires, c.evictions] for c in charges
                ]
        return state

    def restore_state(self, entries: dict, now: Optional[float] = None) -> None:
        """Rebuild holds and budget charges from a replayed journal
        table: disruption spent before the crash stays spent, so a
        restart cannot double an eviction budget. Expired entries are
        dropped; surviving expiries are capped at now + hold_s (a
        skewed stamp must not hold a node hostage past one window)."""
        from karpenter_tpu.recovery.journal import key_tuple

        now = self.clock() if now is None else now
        cap = now + self.config.hold_s
        restored = 0
        for k, v in entries.items():
            restored += self._restore_entry(key_tuple(k), v, now, cap)
        if restored:
            logger().info(
                "preemption: restored %d hold/budget entr(ies) from "
                "the journal", restored,
            )

    def _restore_entry(self, key, v, now: float, cap: float) -> int:
        if key[0] == "hold":
            exp = min(float(v), cap)
            if exp > now:
                self._holds[key[1]] = exp
                return 1
        elif key[0] == "cand":
            exp = min(float(v), cap)
            if exp > now:
                self._candidate_holds[(key[1], key[2])] = exp
                return 1
        elif key[0] == "charge":
            live = [
                _Charge(expires=min(float(e), cap), evictions=int(n))
                for e, n in v
                if min(float(e), cap) > now
            ]
            if live:
                self._charges[(key[1], key[2])] = live
                return 1
        return 0

    def _resolve_and_actuate(
        self, view, candidates, plans, now: float
    ) -> Dict[tuple, Optional[dict]]:
        """Greedy acceptance in candidate (priority) order: claim
        victims and target nodes first-come, defer conflicting or
        over-budget plans to a later round."""
        by_name = view.by_name()
        claimed_victims: Set[tuple] = set()
        claimed_nodes: Set[str] = set()
        results: Dict[tuple, Optional[dict]] = {}
        for pod, plan in zip(candidates, plans):
            key = (pod.metadata.namespace, pod.metadata.name)
            if plan is None:
                results[key] = None
                continue
            if not plan["evictions"]:
                # fits without eviction: nothing to actuate — the
                # ordinary schedule/scale path owns zero-disruption
                # placement
                results[key] = plan
                continue
            node = plan["node"]
            group = by_name[node].group if node in by_name else None
            if (
                node in claimed_nodes
                or any(v in claimed_victims for v in plan["evictions"])
            ):
                self._c_deferred.inc("-", "-")
                results[key] = None
                continue
            if self._budget_left(group, node) < len(plan["evictions"]):
                self._c_deferred.inc("-", "-")
                logger().info(
                    "preemption deferred for %s/%s: eviction budget "
                    "exhausted on %s", key[0], key[1], node,
                )
                results[key] = None
                continue
            evicted = self._actuate_with_charge(plan, group, node, now)
            if not evicted:
                results[key] = None
                continue
            claimed_nodes.add(node)
            claimed_victims.update(plan["evictions"])
            results[key] = self._finish_accepted(
                key, node, plan, evicted, now
            )
        return results

    def _actuate_with_charge(
        self, plan: dict, group, node: str, now: float
    ) -> List[tuple]:
        """WRITE-AHEAD actuation: the hold and the FULL plan's budget
        charge journal BEFORE any eviction lands, so a crash mid-batch
        restores with the disruption already charged — a restarted
        controller can never spend a budget twice. What actually
        happened is reconciled after actuation: zero evictions releases
        the charge and hold, a partial set adjusts the charge down to
        the evictions that landed."""
        self._holds[node] = now + self.config.hold_s
        self._journal_hold(node, self._holds[node])
        self._charge(group, len(plan["evictions"]), now, node)
        evicted = self._actuate(plan)
        bkey = self._budget_key(group, node)
        if not evicted:
            self._charges[bkey].pop()
            if not self._charges[bkey]:
                del self._charges[bkey]
            self._journal_charges(bkey)
            self._holds.pop(node, None)
            self._journal_hold(node, None)
        elif len(evicted) < len(plan["evictions"]):
            self._charges[bkey][-1].evictions = len(evicted)
            self._journal_charges(bkey)
        return evicted

    def _finish_accepted(
        self, key, node: str, plan: dict, evicted: List[tuple],
        now: float,
    ) -> Optional[dict]:
        """Post-actuation accounting. A FULLY actuated plan is
        accepted (candidate held for hold_s). A partial set — a store
        conflict vetoed some victims — is NOT: the freed capacity may
        not admit the candidate, so it re-plans promptly; the
        disruption that DID happen stays charged and the node stays
        held."""
        if len(evicted) < len(plan["evictions"]):
            self._c_deferred.inc("-", "-")
            logger().warning(
                "preemption partially actuated on %s (%d/%d "
                "evictions); re-planning %s/%s next round",
                node, len(evicted), len(plan["evictions"]),
                key[0], key[1],
            )
            return None
        self._candidate_holds[key] = now + self.config.hold_s
        self._journal_candidate_hold(key)
        self._c_plans.inc("-", "-")
        logger().info(
            "preemption: evicted %d pod(s) from %s to admit %s/%s",
            len(evicted), node, key[0], key[1],
        )
        return dict(plan, evictions=evicted)

    def _actuate(self, plan: dict) -> List[tuple]:
        """Evict the plan's victims (store delete — the in-process
        Eviction analog). Conditional per victim: a pod already gone
        (raced by its own lifecycle) is skipped, never double-counted;
        a store conflict vetoes just that victim and the plan reports
        what it actually evicted."""
        evicted = []
        for i, (namespace, name) in enumerate(plan["evictions"]):
            if i:
                # the mid-eviction-batch kill point
                inject("process.crash.evict")
            pod = self.store.try_get("Pod", namespace, name)
            if pod is None or not pod.spec.node_name:
                continue  # already gone or already unbound
            try:
                self.store.delete("Pod", namespace, name)
            except Exception as e:  # noqa: BLE001 — racing writers:
                # the next plan re-evaluates from fresh state
                logger().warning(
                    "preemption eviction %s/%s failed: %s",
                    namespace, name, e,
                )
                continue
            evicted.append((namespace, name))
            self._c_evictions.inc("-", "-")
        return evicted
