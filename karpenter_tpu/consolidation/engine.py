"""Consolidation engine: safety gates, state machine, actuation.

The planner (planner.py) answers the pure fit question — which nodes'
pods re-pack onto the remainder. This module wraps that verdict in the
operational safety a production drain needs:

  * DO-NOT-DISRUPT: a node, or any pod on it, annotated
    `karpenter.sh/do-not-disrupt: "true"` is never a candidate.
  * COOLDOWN / HYSTERESIS: a node whose bound-pod set changed within
    `cooldown_s` is not a candidate — a node that just received pods is
    exactly the node the scheduler is actively using, and draining it
    would thrash. First sight of a node starts its clock (conservative:
    a restarted engine waits out one cooldown before touching anything).
  * DISRUPTION BUDGETS: at most `budget_per_group` nodes of one group
    are in flight (cordoned/draining) at a time, so consolidation can
    never take a group below quorum in one sweep.
  * TWO-PHASE cordon → verify → drain: a drainable candidate is first
    CORDONED (spec.unschedulable, so the scheduler stops adding pods and
    the next plan's receiver mask excludes it), then RE-VERIFIED against
    fresh cluster state for `verify_s` before the drain is approved. A
    verdict that flips during the soak un-cordons the node and counts a
    veto — the cluster changed under us, and the safe answer is to put
    the node back.

Actuation is intent-based, riding the existing control flow rather than
bypassing it: an approved drain decrements the owning ScalableNodeGroup's
spec.replicas through the store's scale subresource (the same door the
HorizontalAutoscaler writes), and the ScalableNodeGroup controller's
normal spec-vs-observed loop performs the provider call. The controller
reports the scale-down back (`on_scale_down`), at which point the engine
finalizes: the drained Node object is deleted and the FSM entry retires.

Metrics (subsystem "consolidation", published through the runtime
registry): candidates evaluated, drains planned/vetoed/actuated, nodes
in flight, and the batched-eval latency.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional

from karpenter_tpu.consolidation import planner as P
from karpenter_tpu.faults import inject
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "consolidation"

CANDIDATES_EVALUATED = "candidates_evaluated_total"
DRAINS_PLANNED = "drains_planned_total"
DRAINS_VETOED = "drains_vetoed_total"
DRAINS_ACTUATED = "drains_actuated_total"
IN_FLIGHT = "in_flight"
BATCH_EVAL_MS = "batch_eval_ms"
BATCH_CANDIDATES = "batch_candidates"

# FSM phases
CORDONED = "cordoned"  # unschedulable, soaking through verify_s
APPROVED = "approved"  # re-verified; waiting for the controller to scale
DRAINING = "draining"  # spec.replicas decremented; provider call pending
UNCORDONING = "uncordoning"  # veto'd but the uncordon write failed: a
# node must never stay unschedulable because one store write conflicted
# (e.g. a status heartbeat landing mid-update), so the entry lingers in
# this phase and every plan retries the write until it lands

STATE_ANNOTATION = "karpenter.sh/consolidation-state"


@dataclass
class ConsolidationConfig:
    plan_interval_s: float = 30.0
    cooldown_s: float = 300.0
    verify_s: float = 60.0
    budget_per_group: int = 1
    max_candidates: int = 64
    buckets: int = 32
    backend: Optional[str] = None  # None = the service's default
    # how long a DRAINING node may wait for its scale-down to be
    # observed before the drain is vetoed and the node returned to
    # service. Bounds two failure loops: a concurrent spec writer (an
    # HPA targeting the same group) repeatedly reverting the replica
    # decrement, and a provider that never converges — either would
    # otherwise hold the node cordoned and the group's budget slot
    # forever.
    drain_timeout_s: float = 600.0


@dataclass
class _InFlight:
    node: str
    group: tuple  # (namespace, producer, ref)
    phase: str
    since: float


class ConsolidationEngine:
    """Owns the plan cadence and the per-node drain state machine."""

    def __init__(
        self,
        store,
        solver_service,
        registry: Optional[GaugeRegistry] = None,
        config: Optional[ConsolidationConfig] = None,
        clock=None,
    ):
        self.store = store
        self.service = solver_service
        # optional coordination seam: a callable returning node names
        # some OTHER disruption engine currently owns (the preemption
        # engine's active_nodes — runtime.py wires it). Guarded nodes
        # are never consolidation candidates, so the two engines cannot
        # fight over one node (docs/preemption.md "Coordination").
        self.node_guard = None
        # crash safety (karpenter_tpu/recovery, docs/resilience.md):
        # `journal` is a JournalHandle recording every FSM transition so
        # a restarted controller resumes each node's phase instead of
        # re-cordoning; `disruption_gate` is the recovery warm-up gate —
        # while it returns False (fleet state unconfirmed after a
        # restart) no planning happens at all
        self.journal = None
        self.disruption_gate = None
        self.config = config or ConsolidationConfig()
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self.clock = clock or _time.monotonic
        self._in_flight: Dict[str, _InFlight] = {}
        # node -> (bound-pod-set signature, last-churn timestamp)
        self._churn: Dict[str, tuple] = {}
        self._last_plan: Optional[float] = None
        reg = self.registry.register
        self._c_evaluated = reg(SUBSYSTEM, CANDIDATES_EVALUATED,
                                kind="counter")
        self._c_planned = reg(SUBSYSTEM, DRAINS_PLANNED, kind="counter")
        self._c_vetoed = reg(SUBSYSTEM, DRAINS_VETOED, kind="counter")
        self._c_actuated = reg(SUBSYSTEM, DRAINS_ACTUATED, kind="counter")
        self._g_in_flight = reg(SUBSYSTEM, IN_FLIGHT)
        self._g_eval_ms = reg(SUBSYSTEM, BATCH_EVAL_MS)
        self._g_candidates = reg(SUBSYSTEM, BATCH_CANDIDATES)

    # -- plan cadence ------------------------------------------------------

    def maybe_plan(self, now: Optional[float] = None) -> None:
        """Plan at most once per `plan_interval_s`; the ScalableNodeGroup
        controller calls this every reconcile, so the cadence is bounded
        here rather than in the caller."""
        now = self.clock() if now is None else now
        if (
            self._last_plan is not None
            and now - self._last_plan < self.config.plan_interval_s
        ):
            return
        self.plan(now)

    def plan(self, now: Optional[float] = None) -> Dict[str, bool]:
        """One full planning round: snapshot, advance the FSM, evaluate
        new candidates in one batched solver call, cordon the drainable
        ones. Returns {candidate: verdict} for observability/tests."""
        now = self.clock() if now is None else now
        if self.disruption_gate is not None and not self.disruption_gate():
            # recovery warm-up: fleet state is unconfirmed after a
            # restart — plan nothing, and do NOT stamp _last_plan so the
            # first post-warm-up reconcile plans immediately
            return {}
        self._last_plan = now
        groups = P.discover_groups(self.store)
        view = P.cluster_view(self.store, groups)
        by_name = view.by_name()
        self._update_churn(view, now)
        self._drop_vanished(by_name)
        self._retry_uncordons()
        self._expire_stale_drains(now)

        reverify = [
            s.node for s in self._in_flight.values()
            if s.phase == CORDONED and s.node in by_name
        ]
        fresh = self._generate_candidates(view, now)
        names = reverify + fresh
        if not names:
            self._publish(0, 0.0)
            return {}

        t0 = _time.perf_counter()
        verdicts = P.evaluate(
            view, names, self.service,
            buckets=self.config.buckets, backend=self.config.backend,
        )
        eval_ms = (_time.perf_counter() - t0) * 1e3
        self._c_evaluated.inc("-", "-", float(len(names)))

        self._advance_cordoned(reverify, verdicts, now)
        self._cordon_drainable(view, fresh, verdicts, now)
        self._publish(len(names), eval_ms)
        return verdicts

    # -- candidate generation ---------------------------------------------

    def _update_churn(self, view: P.ClusterView, now: float) -> None:
        for nv in view.nodes:
            signature = frozenset(
                (p.metadata.namespace, p.metadata.name) for p in nv.pods
            )
            previous = self._churn.get(nv.name)
            if previous is None or previous[0] != signature:
                self._churn[nv.name] = (signature, now)
        live = {nv.name for nv in view.nodes}
        for name in [n for n in self._churn if n not in live]:
            del self._churn[name]

    def _drop_vanished(self, by_name) -> None:
        for name in [n for n in self._in_flight if n not in by_name]:
            # the node left the cluster out from under the FSM (a manual
            # delete, another actor): nothing left to drain
            del self._in_flight[name]
            self._journal_del(name)

    # -- crash-safe FSM journal (karpenter_tpu/recovery) -------------------

    def _journal_set(self, state: _InFlight) -> None:
        if self.journal is not None:
            self.journal.set(
                ("node", state.node),
                {
                    "group": list(state.group),
                    "phase": state.phase,
                    "since": state.since,
                },
            )

    def _journal_del(self, name: str) -> None:
        if self.journal is not None:
            self.journal.delete(("node", name))

    def snapshot_state(self) -> Dict[str, dict]:
        """Full FSM table for the recovery checkpoint (same layout the
        journal folds to)."""
        from karpenter_tpu.recovery.journal import key_str

        return {
            key_str(("node", s.node)): {
                "group": list(s.group),
                "phase": s.phase,
                "since": s.since,
            }
            for s in self._in_flight.values()
        }

    def restore_state(self, entries: dict, now: Optional[float] = None) -> None:
        """Rebuild the in-flight FSM from a replayed journal table: a
        cordoned node resumes its phase (and its verify soak) instead of
        being re-cordoned from scratch. Restored `since` stamps are
        capped at `now` — the shared clock is wall time, but a skewed
        stamp must never fast-forward a soak."""
        from karpenter_tpu.recovery.journal import key_tuple

        now = self.clock() if now is None else now
        for k, v in entries.items():
            name = key_tuple(k)[1]
            self._in_flight[name] = _InFlight(
                node=name,
                group=tuple(v["group"]),
                phase=v["phase"],
                since=min(float(v["since"]), now),
            )
        if self._in_flight:
            self._publish_in_flight()
            logger().info(
                "consolidation: restored %d in-flight drain(s) from "
                "the journal: %s",
                len(self._in_flight),
                {s.node: s.phase for s in self._in_flight.values()},
            )
        self._release_orphan_cordons()

    def _release_orphan_cordons(self) -> None:
        """Uncordon nodes carrying OUR state annotation with no restored
        FSM entry — a crash between the durable cordon write and its
        journal append leaves exactly this orphan, and the candidate
        gate would otherwise exclude it forever (a cordoned node is
        nobody's receiver). The invariant stands: a node is never left
        unschedulable with nobody owning it."""
        for key in list(self.store.keys("Node")):
            name = key[2]
            if name in self._in_flight:
                continue
            node = self.store.try_get(*key)
            if (
                node is None
                or STATE_ANNOTATION not in node.metadata.annotations
            ):
                continue
            logger().warning(
                "consolidation: releasing orphan cordon on %s (state "
                "annotation present, no journaled FSM entry — crash "
                "between cordon and journal append)", name,
            )
            if not self._uncordon(name):
                # the uncordon write conflicted: adopt the node in
                # UNCORDONING so every plan retries until it lands
                self._in_flight[name] = _InFlight(
                    node=name, group=("", "", ""),
                    phase=UNCORDONING, since=self.clock(),
                )
                self._journal_set(self._in_flight[name])

    @staticmethod
    def _budget_key(group: tuple) -> tuple:
        # budgets bind to the actuation target (namespace, ref) — two
        # producers pointing one ScalableNodeGroup share one budget
        return (group[0], group[2])

    def _budget_left(self, group: tuple) -> int:
        key = self._budget_key(group)
        in_flight = sum(
            1 for s in self._in_flight.values()
            if self._budget_key(s.group) == key
        )
        return self.config.budget_per_group - in_flight

    def _eligible(
        self, nv: P.NodeView, now: float, guarded=frozenset()
    ) -> bool:
        """All the pre-solve gates: in-flight, another engine's node
        hold, actuatability (a group with a ScalableNodeGroup ref),
        schedulability (cordoned nodes are someone's in-progress
        intent), do-not-disrupt, pod-churn cooldown, and the group's
        disruption budget."""
        if nv.name in self._in_flight or nv.do_not_disrupt:
            return False
        if nv.name in guarded:
            return False  # another disruption engine owns this node
        if nv.group is None or not nv.group[2]:
            return False  # no ScalableNodeGroup to shrink: unactuatable
        if not nv.receiver:
            return False  # already cordoned (by us or anyone)
        churn = self._churn.get(nv.name)
        if churn is None or now - churn[1] < self.config.cooldown_s:
            return False
        return self._budget_left(nv.group) > 0

    def _generate_candidates(
        self, view: P.ClusterView, now: float
    ) -> List[str]:
        """Eligible fresh candidates, emptiest-first (the cheapest drains
        evaluate and actuate first), capped at max_candidates."""
        # one guard snapshot per planning round, not per candidate
        guarded = (
            self.node_guard() if self.node_guard is not None
            else frozenset()
        )
        eligible = [
            nv
            for nv in view.nodes
            if self._eligible(nv, now, guarded)
        ]
        eligible.sort(key=lambda nv: (len(nv.pods), nv.name))
        return [nv.name for nv in eligible[: self.config.max_candidates]]

    # -- state machine -----------------------------------------------------

    def _retry_uncordons(self) -> None:
        for name in [
            s.node for s in self._in_flight.values()
            if s.phase == UNCORDONING
        ]:
            self._release(name)

    def _expire_stale_drains(self, now: float) -> None:
        """A DRAINING node whose scale-down is never observed — a
        concurrent spec writer reverting the decrement, a provider that
        never converges — is vetoed past drain_timeout_s and returned
        to service; the replica intent stays whatever its writers last
        wrote (re-raising it here would just be another writer fight)."""
        for name in [
            s.node for s in self._in_flight.values()
            if s.phase == DRAINING
            and now - s.since >= self.config.drain_timeout_s
        ]:
            self._veto(name, "scale-down never observed before timeout")

    def _veto(self, name: str, reason: str) -> None:
        self._c_vetoed.inc("-", "-")
        logger().info("consolidation veto: %s (%s)", name, reason)
        self._release(name)

    def _release(self, name: str) -> None:
        """Uncordon and retire the FSM entry. A failed store write keeps
        the entry in UNCORDONING so the next plan retries — a node must
        never be left unschedulable with nobody owning it."""
        if self._uncordon(name):
            self._in_flight.pop(name, None)
            self._journal_del(name)
            return
        state = self._in_flight.get(name)
        if state is not None:
            state.phase = UNCORDONING
            state.since = self.clock()
            self._journal_set(state)

    def _advance_cordoned(self, reverify, verdicts, now: float) -> None:
        for name in reverify:
            state = self._in_flight[name]
            if not verdicts.get(name, False):
                # the cluster changed under the soak: put the node back
                self._veto(name, "no longer drainable")
            elif now - state.since >= self.config.verify_s:
                state.phase = APPROVED
                self._actuate(state)

    def _cordon_drainable(self, view, fresh, verdicts, now: float) -> None:
        by_name = view.by_name()
        for name in fresh:
            if not verdicts.get(name, False):
                continue
            nv = by_name[name]
            if self._budget_left(nv.group) <= 0:
                continue  # an earlier candidate took the budget slot
            if not self._cordon(name):
                continue
            state = self._in_flight[name] = _InFlight(
                node=name, group=nv.group, phase=CORDONED, since=now
            )
            self._journal_set(state)
            self._c_planned.inc("-", "-")
            logger().info(
                "consolidation: cordoned %s (group %s/%s), verifying "
                "for %.0fs", name, nv.group[0], nv.group[2],
                self.config.verify_s,
            )

    def _cordon(self, name: str) -> bool:
        return self._set_schedulable(name, False)

    def _uncordon(self, name: str) -> bool:
        return self._set_schedulable(name, True)

    def _node_key(self, name: str):
        """Nodes are cluster-scoped but stored under whatever namespace
        their ObjectMeta carries; resolve by name across the kind."""
        for key in self.store.keys("Node"):
            if key[2] == name:
                return key
        return None

    def _set_schedulable(self, name: str, schedulable: bool) -> bool:
        key = self._node_key(name)
        node = self.store.try_get(*key) if key else None
        if node is None:
            return False
        node.spec.unschedulable = not schedulable
        if schedulable:
            node.metadata.annotations.pop(STATE_ANNOTATION, None)
        else:
            node.metadata.annotations[STATE_ANNOTATION] = CORDONED
        try:
            self.store.update(node)
            return True
        except Exception as e:  # noqa: BLE001 — racing writers: next
            # plan retries from fresh state rather than crashing the tick
            logger().warning("consolidation cordon %s failed: %s", name, e)
            return False

    # -- actuation ---------------------------------------------------------

    def _actuate(self, state: _InFlight) -> None:
        """Decrement the owning ScalableNodeGroup's spec.replicas through
        the scale subresource — the same intent door the autoscaler
        writes; the ScalableNodeGroup controller's spec-vs-observed loop
        then performs the provider call.

        The DRAINING transition is journaled WRITE-AHEAD (before the
        scale write): a crash between the journal record and the store
        write restores to DRAINING whose scale-down is never observed —
        drain_timeout_s then vetoes it safely. The reverse order would
        restore to APPROVED after a landed decrement and decrement
        AGAIN on the next plan: one drain, two replicas gone."""
        namespace, _, ref = state.group
        state.phase = DRAINING
        state.since = self.clock()  # drain_timeout_s measures THIS phase
        self._journal_set(state)
        inject("process.crash.drain")  # the mid-drain kill point
        try:
            scale = self.store.get_scale(
                "ScalableNodeGroup", namespace, ref
            )
            current = (
                scale.spec_replicas
                if scale.spec_replicas is not None
                else scale.status_replicas
            )
            if current is None or current <= 0:
                raise RuntimeError(
                    f"group {namespace}/{ref} has no replicas to shed"
                )
            scale.spec_replicas = current - 1
            self.store.update_scale("ScalableNodeGroup", scale)
        except Exception as e:  # noqa: BLE001 — a missing/conflicted
            # group vetoes the drain: uncordon and retry from scratch
            self._veto(
                state.node,
                f"actuation failed ({type(e).__name__}: {e})",
            )
            return
        logger().info(
            "consolidation: draining %s (scaled %s/%s to %d)",
            state.node, namespace, ref, current - 1,
        )

    def pending_drains(self, namespace: str, group_name: str) -> List[str]:
        """Nodes in the DRAINING phase for one ScalableNodeGroup — what
        the controller reports in its scale-down condition."""
        return sorted(
            s.node for s in self._in_flight.values()
            if s.phase == DRAINING
            and s.group[0] == namespace
            and s.group[2] == group_name
        )

    def on_scale_down(
        self, namespace: str, group_name: str, count: int = 1
    ) -> List[str]:
        """The ScalableNodeGroup controller observed an actuated
        scale-down of this group: finalize up to `count` draining nodes
        (delete the Node object — the provider is removing the capacity)
        and retire their FSM entries. Returns the finalized node names."""
        finalized = []
        for name in self.pending_drains(namespace, group_name)[:count]:
            try:
                key = self._node_key(name)
                if key is not None:
                    self.store.delete(*key)
            except Exception:  # noqa: BLE001 — already gone is fine
                pass
            del self._in_flight[name]
            self._journal_del(name)
            self._c_actuated.inc("-", "-")
            finalized.append(name)
            logger().info("consolidation: drained %s", name)
        if finalized:
            self._publish_in_flight()
        return finalized

    # -- metrics -----------------------------------------------------------

    def in_flight(self) -> Dict[str, str]:
        """{node: phase} — observability and test surface."""
        return {s.node: s.phase for s in self._in_flight.values()}

    def _publish_in_flight(self) -> None:
        self._g_in_flight.set("-", "-", float(len(self._in_flight)))

    def _publish(self, candidates: int, eval_ms: float) -> None:
        self._publish_in_flight()
        self._g_candidates.set("-", "-", float(candidates))
        if candidates:
            self._g_eval_ms.set("-", "-", eval_ms)
