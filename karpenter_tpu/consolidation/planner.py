"""Consolidation planner: batched on-device node-drain feasibility.

The reference Karpenter only ever moves replica COUNTS; it never asks
which concrete nodes are safe to remove, so fragmented groups stay
over-provisioned forever. The consolidation question — "for each
candidate node, can its pods re-pack onto the remainder of the
cluster?" — is one masked bin-pack per candidate, and the mask is the
only thing that differs between candidates, which makes the whole
evaluation one batched device call:

  * the GROUP axis is the cluster's nodes themselves, one column per
    node, allocatable = that node's FREE capacity (allocatable minus the
    scheduler-effective requests of its bound pods, clipped at zero);
  * the POD axis of candidate c is the pods bound to c, re-injected as
    pending rows (scheduler-effective requests + one 'pods' slot);
  * per-candidate masking rides the existing `pod_group_forbidden`
    operand: the candidate's own column is forbidden (a drained node
    cannot receive its own pods back), as is every receiver that is not
    ready+schedulable and every (pod, node) pair ruled out by
    nodeSelector, required node affinity, or an untolerated hard taint;
  * a candidate is DRAINABLE iff the masked bin-pack fits everything:
    zero unschedulable rows and `nodes_needed <= 1` for every column —
    each column is one real node, so needing a second node of that shape
    means the free capacity does not absorb the drain.

All candidates share one operand shape bucket (the pod axis floors at
the service ladder's 256 rung; the node axis is the same cluster for
every candidate), so `SolverService.consolidate` stacks them into ONE
`lax.map` dispatch and candidate-count jitter never recompiles.

The verdict is SUFFICIENT, not necessary: assignment routes each pod to
its single best feasible receiver and sizes quantize UP into buckets, so
a drain that only fits by SPLITTING a pod set across receivers that each
individually overflow can be vetoed spuriously. A spurious veto keeps a
node; a spurious approval would strand pods — the planner only errs in
the safe direction, the same posture as the scale-up signal's
conservative group profiles (encoder._group_profile).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import (
    Taint,
    affinity_shape,
    is_ready_and_schedulable,
    matches_affinity_shape,
    matches_selector,
)
from karpenter_tpu.metrics.producers.pendingcapacity.constants import (
    DEFAULT_PODS_PER_NODE,
)
from karpenter_tpu.ops.binpack import BinPackInputs
from karpenter_tpu.store.columnar import RESOURCE_PODS, is_counted

# Pods (or nodes) carrying this annotation with value "true" are never
# disrupted by consolidation (the karpenter.sh operator contract).
DO_NOT_DISRUPT = "karpenter.sh/do-not-disrupt"

_BASE_RESOURCES = ("cpu", "memory")


@dataclass
class NodeView:
    """One node's consolidation-relevant state, computed once per plan."""

    name: str
    node: object
    pods: List[object]  # bound, non-terminal (the occupancy set)
    free: Dict[str, float]  # allocatable minus reserved, >= 0
    group: Optional[Tuple[str, str, str]] = None  # (ns, producer, ref)
    receiver: bool = True  # ready + schedulable: may absorb drains
    do_not_disrupt: bool = False  # node or any pod opted out


@dataclass
class ClusterView:
    """The columnar consolidation snapshot: every node with its bound
    pods, free capacity, and group membership."""

    nodes: List[NodeView] = field(default_factory=list)

    def by_name(self) -> Dict[str, NodeView]:
        return {v.name: v for v in self.nodes}


def discover_groups(store) -> List[Tuple[str, str, dict, str]]:
    """(namespace, producer name, node selector, nodeGroupRef) for every
    pendingCapacity producer, in deterministic key order — the same
    group axis the scale-up solve uses. The ref names the
    ScalableNodeGroup (in the producer's namespace) that consolidation
    shrinks; groups without a ref are observed but never actuated."""
    groups = []
    for mp in sorted(
        store.list("MetricsProducer"),
        key=lambda m: (m.metadata.namespace, m.metadata.name),
    ):
        if mp.spec.pending_capacity is None:
            continue
        selector = mp.spec.pending_capacity.node_selector
        if not isinstance(selector, dict):
            continue  # poisoned spec: row-isolated out, like solve_pending
        groups.append(
            (
                mp.metadata.namespace,
                mp.metadata.name,
                selector,
                getattr(mp.spec.pending_capacity, "node_group_ref", ""),
            )
        )
    return groups


def _opted_out(obj) -> bool:
    return (
        obj.metadata.annotations.get(DO_NOT_DISRUPT, "").lower() == "true"
    )


def _free_capacity(node, pods) -> Dict[str, float]:
    """allocatable minus the scheduler-effective requests of the bound
    pods (plus their 'pods' slots), clipped at zero — what the node can
    still absorb."""
    free = {r: q.to_float() for r, q in node.status.allocatable.items()}
    if free.get(RESOURCE_PODS, 0.0) <= 0:
        free[RESOURCE_PODS] = float(DEFAULT_PODS_PER_NODE)
    free[RESOURCE_PODS] -= len(pods)
    for pod in pods:
        for r, q in pod.effective_requests().items():
            free[r] = free.get(r, 0.0) - q.to_float()
    return {r: max(0.0, v) for r, v in free.items()}


def cluster_view(store, groups=None) -> ClusterView:
    """Build the snapshot: one store listing for nodes, the pods-by-node
    index for occupancy, host float math for free capacity. Host cost is
    O(nodes + bound pods) per plan — the per-candidate fit math is what
    the device evaluates."""
    if groups is None:
        groups = discover_groups(store)
    view = ClusterView()
    for node in sorted(
        store.list("Node"), key=lambda n: n.metadata.name
    ):
        pods = [
            p
            for p in store.pods_on_node(node.metadata.name)
            if is_counted(p)
        ]
        group = next(
            (
                (ns, name, ref)
                for ns, name, selector, ref in groups
                if matches_selector(node.metadata.labels, selector)
            ),
            None,
        )
        view.nodes.append(
            NodeView(
                name=node.metadata.name,
                node=node,
                pods=pods,
                free=_free_capacity(node, pods),
                group=group,
                receiver=is_ready_and_schedulable(node),
                do_not_disrupt=_opted_out(node)
                or any(_opted_out(p) for p in pods),
            )
        )
    return view


def is_extended_resource(resource: str) -> bool:
    return resource not in _BASE_RESOURCES and resource != RESOURCE_PODS


def resource_universe_for(view: ClusterView, pods) -> List[str]:
    """cpu/memory + every extended resource in the view's node free
    capacity or the given pods' requests, the 'pods' slot axis always
    LAST — THE single universe rule both disruption planners encode
    against (preemption/planner.py reuses it over its candidate +
    victim pod set; a change here moves both in lockstep)."""
    extended = set()
    for nv in view.nodes:
        extended.update(r for r in nv.free if is_extended_resource(r))
    for pod in pods:
        extended.update(
            r for r in pod.effective_requests()
            if is_extended_resource(r)
        )
    return [*_BASE_RESOURCES, *sorted(extended), RESOURCE_PODS]


def request_row(pod, resources: List[str]) -> np.ndarray:
    """f32[R]: the pod's scheduler-effective requests gathered onto the
    universe axis, its one 'pods' slot included — the single per-pod
    row encoding both disruption planners share."""
    row = np.zeros(len(resources), np.float32)
    requests = {
        r: q.to_float() for r, q in pod.effective_requests().items()
    }
    requests[RESOURCE_PODS] = 1.0
    for r, resource in enumerate(resources):
        row[r] = requests.get(resource, 0.0)
    return row


def _resource_universe(view: ClusterView, candidates: List[NodeView]):
    """The consolidation universe: node free capacity + the DRAIN
    candidates' bound pods (the rows that re-pack)."""
    return resource_universe_for(
        view, (pod for nv in candidates for pod in nv.pods)
    )


def _pod_compatible(pod, node_labels: dict, hard_taints: list) -> bool:
    """Host-side feasibility mask for one (pod, receiver) pair: the same
    constraints the scale-up encoder expresses as bitset matmuls, folded
    into the forbidden operand at consolidation scale (pods-on-one-node
    x nodes, KBs not MBs)."""
    if not matches_selector(node_labels, pod.spec.node_selector):
        return False
    for taint in hard_taints:
        if not any(
            tol.tolerates(taint) for tol in pod.spec.tolerations
        ):
            return False
    shape = affinity_shape(pod.spec.affinity)
    if shape and not matches_affinity_shape(node_labels, shape):
        return False
    return True


def build_problems(
    view: ClusterView, candidate_names: List[str]
) -> Tuple[List[str], List[BinPackInputs], List[str]]:
    """One masked BinPackInputs per candidate with bound pods.

    Returns (solved_names, inputs, trivially_drainable): a candidate
    with zero bound pods needs no solve — there is nothing to re-pack —
    so it is split out rather than encoded as a degenerate zero-row
    problem. Every solved candidate's inputs share the node axis and the
    resource universe, so they land in one service shape bucket."""
    by_name = view.by_name()
    candidates = [by_name[n] for n in candidate_names]
    resources = _resource_universe(view, candidates)
    col = {nv.name: t for t, nv in enumerate(view.nodes)}
    free, node_labels, hard_taints, receiver_ok = _node_axis(
        view, resources
    )
    solved, inputs, trivial = [], [], []
    for nv in candidates:
        if not nv.pods:
            trivial.append(nv.name)
            continue
        solved.append(nv.name)
        inputs.append(
            _candidate_inputs(
                nv, resources, free, receiver_ok, col[nv.name],
                node_labels, hard_taints,
            )
        )
    return solved, inputs, trivial


def _node_axis(view: ClusterView, resources):
    """The shared group-axis operands: free-capacity matrix, per-node
    label dicts, per-node hard taints, and the receiver mask."""
    free = np.zeros((len(view.nodes), len(resources)), np.float32)
    for t, nv in enumerate(view.nodes):
        for r, resource in enumerate(resources):
            free[t, r] = nv.free.get(resource, 0.0)
    node_labels = [dict(nv.node.metadata.labels) for nv in view.nodes]
    hard_taints = [
        [
            Taint(key=t.key, value=t.value, effect=t.effect)
            for t in nv.node.spec.taints
            if t.effect in ("NoSchedule", "NoExecute")
        ]
        for nv in view.nodes
    ]
    receiver_ok = np.array([nv.receiver for nv in view.nodes], bool)
    return free, node_labels, hard_taints, receiver_ok


def _candidate_inputs(
    nv, resources, free, receiver_ok, self_col, node_labels, hard_taints
) -> BinPackInputs:
    """The one masked problem for candidate `nv`: its pods as pending
    rows, the shared node axis, its own column (and every incompatible
    pair) forbidden."""
    p, n_groups = len(nv.pods), free.shape[0]
    pod_requests = np.zeros((p, len(resources)), np.float32)
    forbidden = np.zeros((p, n_groups), bool)
    forbidden[:, ~receiver_ok] = True
    forbidden[:, self_col] = True  # never back onto the drain
    for i, pod in enumerate(nv.pods):
        pod_requests[i] = request_row(pod, resources)
        for t in range(n_groups):
            if not forbidden[i, t] and not _pod_compatible(
                pod, node_labels[t], hard_taints[t]
            ):
                forbidden[i, t] = True
    return BinPackInputs(
        pod_requests=pod_requests,
        pod_valid=np.ones(p, bool),
        # taints/selectors/affinity are folded into the forbidden mask
        # above; the bitset operands stay width-1 zeros (the service
        # pads them to its floors)
        pod_intolerant=np.zeros((p, 1), bool),
        pod_required=np.zeros((p, 1), bool),
        group_allocatable=free,
        group_taints=np.zeros((n_groups, 1), bool),
        group_labels=np.zeros((n_groups, 1), bool),
        pod_group_forbidden=forbidden,
    )


def drainable(output) -> bool:
    """The drain verdict for one masked solve: everything re-packed
    (zero unschedulable weight) and no column needs a second node of
    its shape — each column IS one real node's free capacity."""
    return bool(
        int(np.asarray(output.unschedulable)) == 0
        and (np.asarray(output.nodes_needed) <= 1).all()
    )


def evaluate(
    view: ClusterView,
    candidate_names: List[str],
    service,
    buckets: int = 32,
    backend: Optional[str] = None,
) -> Dict[str, bool]:
    """{candidate: drainable} for every named candidate — the batched
    front door: one `service.consolidate` call (one device dispatch per
    shape bucket), trivially-empty candidates short-circuited."""
    solved, inputs, trivial = build_problems(view, candidate_names)
    verdicts = {name: True for name in trivial}
    if inputs:
        outputs = service.consolidate(
            inputs, buckets=buckets, backend=backend
        )
        for name, output in zip(solved, outputs):
            verdicts[name] = drainable(output)
    return verdicts
