"""Consolidation: batched on-device node-drain planning
(docs/consolidation.md).

Public surface:

  * ConsolidationEngine — safety gates + cordon→verify→drain state
    machine + actuation through the scale subresource
  * ConsolidationConfig — knobs (cadence, cooldown, verify soak,
    per-group budgets, candidate cap)
  * planner helpers — cluster_view / build_problems / evaluate /
    drainable, the pure fit math under the engine
  * DO_NOT_DISRUPT — the opt-out annotation
"""

from karpenter_tpu.consolidation.engine import (
    SUBSYSTEM,
    ConsolidationConfig,
    ConsolidationEngine,
)
from karpenter_tpu.consolidation.planner import (
    DO_NOT_DISRUPT,
    ClusterView,
    NodeView,
    build_problems,
    cluster_view,
    discover_groups,
    drainable,
    evaluate,
)

__all__ = [
    "SUBSYSTEM",
    "ConsolidationConfig",
    "ConsolidationEngine",
    "DO_NOT_DISRUPT",
    "ClusterView",
    "NodeView",
    "build_problems",
    "cluster_view",
    "discover_groups",
    "drainable",
    "evaluate",
]
