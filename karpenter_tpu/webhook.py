"""Admission webhook server: AdmissionReview v1 validate + mutate endpoints.

The reference registers a Validator/Defaulter admission webhook per CRD on
the manager's webhook server, port 9443 with cert-manager-injected certs
(reference: pkg/controllers/manager.go:61-68; cmd/controller/main.go:50;
config/webhook/). In the TPU build the in-process store already validates
on write, so this server exists for *real-cluster mode*: when the CRDs are
installed on an actual kube-apiserver (config/ manifests), this process
serves the same ValidatingWebhookConfiguration / MutatingWebhookConfiguration
endpoints the reference does, reusing the exact validate()/default() methods
the store path uses — one source of truth for admission rules.

Wire shape is upstream admission.k8s.io/v1: POST an AdmissionReview whose
.request.object is the manifest; the response carries allowed/status for
validation and a base64 JSONPatch for defaulting.
"""

from __future__ import annotations

import base64
import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import urlsplit

from karpenter_tpu.api.serialization import from_manifest, to_dict

# arm upper-layer validation hooks (e.g. the algorithm-annotation check the
# autoscaler's registry contributes): an admission server must enforce the
# same rules regardless of which process hosts it
import karpenter_tpu.autoscaler.algorithms  # noqa: F401
from karpenter_tpu.utils.log import logger

log = logger()

ADMISSION_API_VERSION = "admission.k8s.io/v1"


def json_patch(before: dict, after: dict, path: str = "") -> List[dict]:
    """RFC 6902 ops transforming `before` into `after` (add/replace/remove).

    Defaulting only ever fills absent fields, but UPDATE-time mutation can
    in principle rewrite any subtree, so all three ops are produced.
    """
    ops: List[dict] = []
    for key in before:
        escaped = str(key).replace("~", "~0").replace("/", "~1")
        p = f"{path}/{escaped}"
        if key not in after:
            ops.append({"op": "remove", "path": p})
        elif isinstance(before[key], dict) and isinstance(after[key], dict):
            ops.extend(json_patch(before[key], after[key], p))
        elif before[key] != after[key]:
            ops.append({"op": "replace", "path": p, "value": after[key]})
    for key in after:
        if key not in before:
            escaped = str(key).replace("~", "~0").replace("/", "~1")
            ops.append({"op": "add", "path": f"{path}/{escaped}", "value": after[key]})
    return ops


# metadata keys the user writes; everything else in metadata is populated
# by the apiserver (generation, managedFields, uid, creationTimestamp,
# resourceVersion, ownerReferences, ...) and must not trip strict decode —
# but the SPEC stays strict: silently dropping a typo'd spec key is
# misconfig that "works" (see serialization.from_dict docstring).
_USER_METADATA_KEYS = ("name", "namespace", "labels", "annotations")


def admission_decode(manifest: dict):
    """Decode a .request.object for admission: strip the server-populated
    parts (metadata bookkeeping, status — which carries RFC3339 condition
    timestamps on UPDATE), then decode the user-authored remainder
    STRICTLY so unknown spec fields are still denied, not dropped."""
    doc = dict(manifest)
    meta = doc.get("metadata")
    if isinstance(meta, dict):
        doc["metadata"] = {
            k: v for k, v in meta.items() if k in _USER_METADATA_KEYS
        }
    doc.pop("status", None)  # status writes don't go through admission
    return from_manifest(doc)


def review_validate(review: dict) -> dict:
    """AdmissionReview request -> AdmissionReview response (validation)."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    try:
        obj = admission_decode(request.get("object") or {})
        obj.validate()
    except Exception as err:  # any admission failure -> denied, message out
        return _response(uid, allowed=False, message=str(err))
    return _response(uid, allowed=True)


def review_mutate(review: dict) -> dict:
    """AdmissionReview request -> response carrying the defaulting patch."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    manifest = request.get("object") or {}
    try:
        # the patch is computed between two admission_decode round-trips,
        # so server-populated fields absent from both sides never appear
        # in the JSONPatch.
        obj = admission_decode(manifest)
        before = to_dict(obj)
        obj.default()
        after = to_dict(obj)
    except Exception as err:
        return _response(uid, allowed=False, message=str(err))
    ops = json_patch(before, after)
    response = _response(uid, allowed=True)
    if ops:
        response["response"]["patchType"] = "JSONPatch"
        response["response"]["patch"] = base64.b64encode(
            json.dumps(ops).encode()
        ).decode()
    return response


def _response(uid: str, allowed: bool, message: str = "") -> dict:
    response = {"uid": uid, "allowed": allowed}
    if message:
        response["status"] = {"message": message, "code": 400}
    return {
        "apiVersion": ADMISSION_API_VERSION,
        "kind": "AdmissionReview",
        "response": response,
    }


class WebhookServer:
    """Serves /validate and /mutate (reference webhook port: 9443).

    TLS is required by real apiservers; pass cert_file/key_file (the
    config/ manifests mount a cert-manager secret at /tmp/k8s-webhook-server
    exactly like the reference's Deployment does). Without certs the server
    speaks plain HTTP — test and local-dev mode.
    port=0 binds an ephemeral port; the bound port is returned by start().
    """

    def __init__(
        self,
        port: int = 9443,
        host: str = "0.0.0.0",
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
    ):
        self.port = port
        self.host = host
        self.cert_file = cert_file
        self.key_file = key_file
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = urlsplit(self.path).path.rstrip("/")
                if path in ("", "/healthz", "/readyz"):
                    self._send(200, b"ok", "text/plain")
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_POST(self):  # noqa: N802
                path = urlsplit(self.path).path.rstrip("/")
                handler = {
                    "/validate": review_validate,
                    "/mutate": review_mutate,
                    # reference-compatible aliases (controller-runtime style)
                    "/validate-autoscaling-karpenter-sh-v1alpha1": review_validate,
                    "/default-autoscaling-karpenter-sh-v1alpha1": review_mutate,
                }.get(path)
                if handler is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    review = json.loads(self.rfile.read(length))
                    body = json.dumps(handler(review)).encode()
                except Exception as err:
                    log.warning("webhook: malformed AdmissionReview: %s", err)
                    self._send(400, str(err).encode(), "text/plain")
                    return
                self._send(200, body, "application/json")

            def _send(self, code: int, body: bytes, content_type: str):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.cert_file and self.key_file:
            context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            context.load_cert_chain(self.cert_file, self.key_file)
            self._server.socket = context.wrap_socket(
                self._server.socket, server_side=True
            )
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
