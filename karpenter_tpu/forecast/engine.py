"""FleetForecaster: the host-side orchestration of predictive scaling.

Owns the pieces the BatchAutoscaler composes each tick
(docs/forecasting.md):

  * the MetricHistoryStore (forecast/history.py) — every observed
    metric sample of every HorizontalAutoscaler lands here, surviving
    engine requeues and deactivations, pruned on HA deletion;
  * ONE batched forecast per tick — every forecast-enabled series in
    the fleet rides a single ForecastInputs matrix through the
    `forecast_fn` seam (SolverService.forecast in production: coalesced
    queue, compile cache, numpy fallback, backend-health FSM);
  * online SKILL tracking — each prediction is remembered until its
    horizon elapses, then scored against what actually happened
    (normalized absolute error folded into a per-HA EWMA). Skill below
    the spec's floor auto-disables blending for that HA: a forecast
    that has been wrong lately doesn't get to provision nodes;
  * the never-block contract — forecast_rows() NEVER raises. Any
    failure (device fault past every service degradation rung, a
    poisoned spec) logs, counts karpenter_forecast_disabled_total, and
    returns no forecasts: the tick proceeds purely reactive, exactly as
    if the subsystem didn't exist.

Metrics: karpenter_forecast_{skill,horizon_value} gauges and
karpenter_forecast_{blend,disabled}_total counters, labeled
{name, namespace} per HorizontalAutoscaler.
"""

from __future__ import annotations

import collections
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.forecast import models as M
from karpenter_tpu.forecast.history import MetricHistoryStore
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "forecast"

# FORECASTING condition reasons (api/conditions.py carries the type)
REASON_WARMING_UP = "ForecastWarmingUp"
REASON_SKILL_DEGRADED = "ForecastSkillDegraded"
REASON_UNAVAILABLE = "ForecastUnavailable"

_ERR_FLOOR = 1e-6  # normalization floor for the skill error ratio
# query-pool dedupe window: N autoscalers sharing one query read it N
# times per tick — appending each read would shrink the pool's apparent
# sample spacing N-fold and wreck any series seeded from it
_QUERY_DEDUPE_S = 1.0


def _ha_key(ha) -> Tuple[str, str]:
    return (ha.metadata.namespace, ha.metadata.name)


def _series_key(ha, metric_index: int) -> tuple:
    return ("ha", ha.metadata.namespace, ha.metadata.name, metric_index)


def query_key(name: str, labels: Optional[dict]) -> tuple:
    """Key for raw metrics-client observations (the warm pool)."""
    return ("q", name, tuple(sorted((labels or {}).items())))


def _drop_keys(table: Dict, predicate) -> None:
    """Delete every key matching predicate (prune helper)."""
    for key in [k for k in table if predicate(k)]:
        del table[key]


class FleetForecaster:
    """One per runtime; see module docstring.

    `forecast_fn` is the device seam: any (ForecastInputs) ->
    ForecastOutputs callable — SolverService.forecast in production
    (runtime.py wiring), the jitted kernel directly when standalone.
    """

    def __init__(
        self,
        forecast_fn=None,
        registry=None,
        clock=_time.time,
        capacity: int = 64,
        stale_max_age_s: float = 60.0,
        skill_alpha: float = 0.3,
    ):
        self.forecast_fn = (
            forecast_fn if forecast_fn is not None else M.forecast_jit
        )
        self.clock = clock
        self.stale_max_age_s = stale_max_age_s
        self.skill_alpha = skill_alpha
        self.history = MetricHistoryStore(capacity=capacity)
        # crash safety (karpenter_tpu/recovery): skill updates journal
        # through this handle so the blend resumes after a restart with
        # its earned skill — neither an optimistic reset (a forecaster
        # that was WRONG pre-crash would immediately provision nodes
        # again) nor a cold start (one that was RIGHT would stop
        # helping). History appends journal via history.journal.
        self.journal = None
        # (ns, name) -> skill EWMA in [0, 1]; optimistic start (1.0) so a
        # fresh forecaster blends until its predictions prove bad
        self._skill: Dict[tuple, float] = {}
        # (ns, name, metric_index) -> (point, sigma2, expires_at) of the
        # newest batched forecast — the demand DISTRIBUTION the cost
        # subsystem reads as its risk input (docs/cost.md); refreshed
        # each _predict pass, pruned with the HA, dropped by
        # distribution() two horizons after its last refresh
        self._dist: Dict[tuple, Tuple[float, float, float]] = {}
        # series key -> pending (target_time, predicted) awaiting scoring
        self._pending: Dict[tuple, collections.deque] = {}
        # (ns, name) -> (active, reason, message) for the FORECASTING
        # condition, refreshed each forecast_rows pass
        self._verdicts: Dict[tuple, Tuple[bool, str, str]] = {}
        # (ns, name) keys currently holding karpenter_forecast_* gauge
        # series — so a row that DROPS spec.behavior.forecast retires
        # its series (the frozen-series discipline karpenter_cost_*
        # established) instead of pinning the last pre-opt-out skill on
        # dashboards forever
        self._gauged: set = set()
        self._g_skill = self._g_value = None
        self._c_blend = self._c_disabled = None
        if registry is not None:
            self._g_skill = registry.register(SUBSYSTEM, "skill")
            self._g_value = registry.register(SUBSYSTEM, "horizon_value")
            self._c_blend = registry.register(
                SUBSYSTEM, "blend_total", kind="counter"
            )
            self._c_disabled = registry.register(
                SUBSYSTEM, "disabled_total", kind="counter"
            )

    # -- observation paths -------------------------------------------------

    def observe_query(self, metric) -> None:
        """Metrics-client observation hook (metrics/clients.py): every
        successful instant query feeds the query-keyed warm pool.
        Reads landing within the dedupe window of the last sample are
        dropped — same-tick reads from autoscalers sharing a query
        carry no new information and would corrupt the pool's sample
        spacing."""
        key = query_key(metric.name, metric.labels)
        now = self.clock()
        last = self.history.last(key)
        if last is not None and now - last[0] < _QUERY_DEDUPE_S:
            return
        self.history.append(key, now, float(metric.value))

    def stale_value(self, ha, metric_index: int, now: float):
        """Age-bounded last sample for a row whose live metric query
        failed (the stale-metric fix): the value the batch can reuse, or
        None when history is empty/too old to stand in."""
        last = self.history.last(_series_key(ha, metric_index))
        if last is None:
            return None
        t, value = last
        if now - t > self.stale_max_age_s:
            return None
        return value

    def skill(self, namespace: str, name: str) -> float:
        return self._skill.get((namespace, name), 1.0)

    def verdict(self, namespace: str, name: str):
        """(active, reason, message) for the FORECASTING condition."""
        return self._verdicts.get(
            (namespace, name), (False, REASON_WARMING_UP, "no forecast yet")
        )

    def distribution(
        self, namespace: str, name: str, metric_index: int
    ) -> Optional[Tuple[float, float]]:
        """(point, sigma2) of the newest forecast for one HA metric —
        the demand distribution the cost subsystem's risk term consumes
        (docs/cost.md); None while the series hasn't forecast yet, and
        None again once a forecast goes two horizons without a refresh
        (the stale entry is dropped, not served)."""
        key = (namespace, name, metric_index)
        entry = self._dist.get(key)
        if entry is None:
            return None
        point, sigma2, expires = entry
        if self.clock() >= expires:
            del self._dist[key]
            return None
        return (point, sigma2)

    def prune(self, namespace: str, name: str) -> None:
        """Forget a deleted HorizontalAutoscaler (HA controller
        on_deleted hook): history, skill, pending scores, gauges."""
        self.history.prune("ha", namespace, name)
        if (
            self._skill.pop((namespace, name), None) is not None
            and self.journal is not None
        ):
            self.journal.delete(("skill", namespace, name))
        self._verdicts.pop((namespace, name), None)
        _drop_keys(
            self._pending, lambda k: k[1] == namespace and k[2] == name
        )
        _drop_keys(
            self._dist, lambda k: k[0] == namespace and k[1] == name
        )
        self._retire_gauges(namespace, name)

    def _retire_gauges(self, namespace: str, name: str) -> None:
        """Drop one HA's karpenter_forecast_* series (deletion AND
        forecast-spec opt-out both land here)."""
        self._gauged.discard((namespace, name))
        if self._g_skill is not None:
            self._g_skill.remove(name, namespace)
            self._g_value.remove(name, namespace)

    # -- crash-safe restore/snapshot (karpenter_tpu/recovery) --------------

    def snapshot_state(self) -> Dict[str, float]:
        """Skill table for the recovery checkpoint."""
        from karpenter_tpu.recovery.journal import key_str

        return {
            key_str(("skill",) + ha_key): value
            for ha_key, value in self._skill.items()
        }

    def restore_state(
        self, skill_entries: dict, history_entries: dict
    ) -> None:
        """Rebuild skill EWMAs and history rings from replayed journal
        tables: the forecast blend resumes where the crashed
        incarnation left it — earned skill, warm series — instead of a
        cold start."""
        from karpenter_tpu.recovery.journal import key_tuple

        for k, value in skill_entries.items():
            key = key_tuple(k)  # ("skill", ns, name)
            self._skill[(key[1], key[2])] = float(value)
        for k, samples in history_entries.items():
            self.history.restore_ring(key_tuple(k), samples)
        if skill_entries or history_entries:
            logger().info(
                "forecast: restored %d skill entr(ies) and %d history "
                "series from the journal",
                len(skill_entries), len(history_entries),
            )

    # -- the per-tick pass -------------------------------------------------

    def forecast_rows(self, rows, now: float) -> Dict[tuple, float]:
        """The BatchAutoscaler's per-tick call: ingest every live row's
        observations, score matured predictions, and forecast every
        eligible series in ONE batched dispatch. Returns
        {(row_index, metric_index): predicted_value}; empty on any
        failure (never raises — module docstring)."""
        try:
            eligible = self._ingest(rows, now)
            if not eligible:
                return {}
            return self._predict(rows, eligible, now)
        except Exception as error:  # noqa: BLE001 — never-block contract
            self._mark_unavailable(rows, error)
            return {}

    def _mark_unavailable(self, rows, error) -> None:
        """The never-block failure posture: log once, stamp every
        forecast-opted row's verdict REASON_UNAVAILABLE — this tick
        scales reactive-only."""
        logger().warning(
            "forecast pass failed (%s: %s); this tick scales "
            "reactive-only", type(error).__name__, error,
        )
        for row in rows:
            if getattr(row.ha.spec.behavior, "forecast", None) is None:
                continue
            ns, name = _ha_key(row.ha)
            self._verdicts[(ns, name)] = (
                False, REASON_UNAVAILABLE, f"forecast failed: {error}"
            )
            if self._c_disabled is not None:
                self._c_disabled.inc(name, ns)

    def fused_plan(self, rows, now: float):
        """Host half 1 of the fused tick's forecast stage
        (ops/fusedtick.py): ingest + operand assembly with NO dispatch
        — the fused program runs the fit in-device and scatters the
        points straight into the decide operands. Returns
        (eligible, ForecastInputs, row/col/need/blend maps) or None
        (nothing eligible, or the forecast_rows failure posture)."""
        try:
            eligible = self._ingest(rows, now)
            if not eligible:
                return None
            inputs = self._build_inputs(eligible, now)
        except Exception as error:  # noqa: BLE001 — never-block contract
            self._mark_unavailable(rows, error)
            return None
        k = len(eligible)
        row_map = np.zeros(k, np.int32)
        col_map = np.zeros(k, np.int32)
        need = np.zeros(k, np.int32)
        blend = np.zeros(k, bool)
        for idx, (i, j, _key, fspec, blend_flag) in enumerate(eligible):
            row_map[idx] = i
            col_map[idx] = j
            # the same sample floor _predict gates on host-side — the
            # kernel compares it against n_valid in-device
            need[idx] = max(int(fspec.min_samples), 2)
            blend[idx] = blend_flag
        return eligible, inputs, row_map, col_map, need, blend

    def fused_commit(self, eligible, out, rows, now: float):
        """Host half 2: the bookkeeping _predict runs after its
        dispatch — distribution refresh, pending scoring queue, skill
        gauges, ledger provenance — given the ForecastOutputs the fused
        program returned. The blend itself already happened in-device;
        the returned dict is the same surface forecast_rows exposes."""
        try:
            return self._commit(rows, eligible, out, now)
        except Exception as error:  # noqa: BLE001 — never-block contract
            self._mark_unavailable(rows, error)
            return {}

    def _ingest(self, rows, now: float) -> List[tuple]:
        """Append observations, mature skill scores, and collect the
        (row_index, metric_index, key, spec) tuples eligible for this
        tick's batched forecast."""
        eligible: List[tuple] = []
        for i, row in enumerate(rows):
            ha = row.ha
            fspec = getattr(ha.spec.behavior, "forecast", None)
            stale = getattr(row, "stale_metrics", set())
            for j, (metric_spec, _target, value) in enumerate(row.observed):
                key = _series_key(ha, j)
                if j not in stale and np.isfinite(value):
                    self._mature(key, _ha_key(ha), now, float(value))
                    self.history.append(key, now, float(value))
            if fspec is None or getattr(row, "custom", False):
                # a row that STOPPED opting in retires its gauge series
                # — skill and pending scores are kept (earned knowledge
                # a re-opt-in resumes from), only the exported series
                # must not freeze at its pre-opt-out value
                key = _ha_key(ha)
                if key in self._gauged:
                    self._retire_gauges(*key)
                continue
            self._seed_from_queries(ha)
            eligible.extend(self._eligible_row(i, row, fspec))
        return eligible

    def _seed_from_queries(self, ha) -> None:
        """Warm-pool seeding: a fresh HA series copies the query-keyed
        history another observer already accumulated."""
        for j, metric_spec in enumerate(ha.spec.metrics):
            key = _series_key(ha, j)
            if self.history.count(key) > 0:
                continue
            if metric_spec.prometheus is None:
                continue
            from karpenter_tpu.metrics.clients import parse_instant_selector

            try:
                name, labels = parse_instant_selector(
                    metric_spec.prometheus.query
                )
            except Exception:  # noqa: BLE001 — unparseable query: no seed
                continue
            self.history.seed(key, query_key(name, labels))

    def _eligible_row(self, i: int, row, fspec) -> List[tuple]:
        """(row_index, metric_index, key, spec, blend) tuples for this
        row's warm series. A skill-gated row still forecasts — in
        SHADOW mode (blend=False): its predictions keep being scored so
        the skill EWMA can actually recover, they just don't raise any
        scale-up decision while below the floor."""
        ns, name = _ha_key(row.ha)
        skill = self.skill(ns, name)
        blend = skill >= fspec.min_skill
        if not blend:
            self._verdicts[(ns, name)] = (
                False,
                REASON_SKILL_DEGRADED,
                f"skill {skill:.3f} below floor {fspec.min_skill:.3f}; "
                "scaling reactive-only until it recovers",
            )
            if self._c_disabled is not None:
                self._c_disabled.inc(name, ns)
        out: List[tuple] = []
        need = max(int(fspec.min_samples), 2)
        short = 0
        for j in range(len(row.observed)):
            key = _series_key(row.ha, j)
            if self.history.count(key) >= need:
                out.append((i, j, key, fspec, blend))
            else:
                short += 1
        if blend:
            # any warm series IS blending: the condition must say so
            # even while a freshly added metric is still warming up
            if out:
                self._verdicts[(ns, name)] = (True, "", "")
            elif short:
                self._verdicts[(ns, name)] = (
                    False,
                    REASON_WARMING_UP,
                    f"{short} metric series below {need} samples",
                )
        return out

    def _mature(self, key, ha_key, now: float, actual: float) -> None:
        """Score every pending prediction for `key` whose horizon has
        elapsed against the freshly observed value. The error is
        normalized by the LARGER of |actual| and the metric's target
        value (the scale replicas are decided at): a queue idling near
        zero overnight with exporter noise must not register as huge
        relative error and strip the skill the morning ramp needs."""
        pending = self._pending.get(key)
        if not pending:
            return
        scored = None
        while pending and pending[0][0] <= now:
            scored = pending.popleft()
        if scored is None:
            return
        _target_t, predicted, scale = scored
        err = abs(predicted - actual) / max(
            abs(actual), scale, _ERR_FLOOR
        )
        sample = max(0.0, 1.0 - err)
        prev = self._skill.get(ha_key, 1.0)
        self._skill[ha_key] = (
            (1.0 - self.skill_alpha) * prev + self.skill_alpha * sample
        )
        if self.journal is not None:
            self.journal.set(("skill",) + ha_key, self._skill[ha_key])

    def _predict(
        self, rows, eligible: List[tuple], now: float
    ) -> Dict[tuple, float]:
        inputs = self._build_inputs(eligible, now)
        out = self.forecast_fn(inputs)
        return self._commit(rows, eligible, out, now)

    def _commit(  # lint: allow-complexity — one guard per per-series concern (gating, distribution, scoring, gauges, provenance)
        self, rows, eligible: List[tuple], out, now: float
    ) -> Dict[tuple, float]:
        from karpenter_tpu.observability import default_ledger

        points = np.asarray(out.point, np.float32)
        sigma2 = np.asarray(out.sigma2, np.float32)
        n_valid = np.asarray(out.n_valid)
        forecasts: Dict[tuple, float] = {}
        # provenance slice (observability/provenance.py): the forecast
        # stage annotates ITS columns of the tick's ledger batch — the
        # predicted value, the skill that gated it, and whether the
        # blend could raise the reactive recommendation (point above
        # observed under an active blend). One record per ROW: the
        # value/skill come from the row's first forecast-eligible
        # metric, the blend flag ORs over ALL its metrics (a blend on
        # metric 1 must not read as 'reactive' just because metric 0
        # carries no forecast). current() is None when the ledger is
        # disabled or no batch is staged.
        ledger_batch = default_ledger().current()
        ledger_rows: Dict[int, list] = {}
        for k, (i, j, key, fspec, blend) in enumerate(eligible):
            if n_valid[k] < max(int(fspec.min_samples), 2):
                continue
            point = float(points[k])
            ns, name = _ha_key(rows[i].ha)
            # the distribution surface (cost subsystem risk input) —
            # refreshed for SHADOW (skill-gated) series too: the risk
            # term gates on its own spec, not on the blend verdict.
            # Expiry-stamped: a series that stops forecasting (broken
            # metric, history reset) must not pin an obsolete spike as
            # the risk input forever — two horizons without a refresh
            # and distribution() forgets it.
            expires = now + 2.0 * max(float(fspec.horizon_seconds), 1.0)
            self._dist[(ns, name, j)] = (point, float(sigma2[k]), expires)
            if blend:
                forecasts[(i, j)] = point
            # remember the prediction for horizon-elapsed scoring —
            # shadow (skill-gated) predictions too, or the skill EWMA
            # could never recover; the deque is bounded so a stalled
            # metric can't grow it. The metric's target value rides
            # along as the error-normalization scale (_mature).
            target = rows[i].observed[j][1]
            try:
                scale = abs(float(target.target_value()))
            except Exception:  # noqa: BLE001 — unscaled metric shapes
                scale = 0.0
            pending = self._pending.setdefault(
                key, collections.deque(maxlen=self.history.capacity)
            )
            pending.append(
                (now + float(fspec.horizon_seconds), point, scale)
            )
            observed = rows[i].observed[j][2]
            if ledger_batch is not None and i < ledger_batch.n:
                entry = ledger_rows.setdefault(
                    i, [point, self.skill(ns, name), blend, False]
                )
                entry[2] = entry[2] or blend
                entry[3] = entry[3] or bool(
                    blend and np.isfinite(observed) and point > observed
                )
            if self._g_skill is not None:
                self._gauged.add((ns, name))
                self._g_skill.set(name, ns, self.skill(ns, name))
                if j == 0:
                    self._g_value.set(name, ns, point)
                if blend and np.isfinite(observed) and point > observed:
                    self._c_blend.inc(name, ns)
        if ledger_rows:
            self._annotate_forecast_rows(ledger_batch, ledger_rows)
        return forecasts

    @staticmethod
    def _annotate_forecast_rows(
        ledger_batch, ledger_rows: Dict[int, list]
    ) -> None:
        """The batch's forecast provenance in one scatter: per row, the
        first eligible metric's predicted value + skill, whether ANY
        metric blends (active), and whether any blend could RAISE the
        reactive recommendation (the same point-above-observed
        condition the blend counter uses)."""
        idx = list(ledger_rows)
        n = ledger_batch.n
        value = np.full(n, np.nan, np.float32)
        skill = np.full(n, np.nan, np.float32)
        active = np.zeros(n, bool)
        blend = np.zeros(n, bool)
        for i, (v, s, a, b) in ledger_rows.items():
            value[i], skill[i], active[i], blend[i] = v, s, a, b
        ledger_batch.annotate_rows(
            idx,
            forecast_value=value,
            forecast_skill=skill,
            forecast_active=active,
            forecast_blend=blend,
        )

    def _build_inputs(
        self, eligible: List[tuple], now: float
    ) -> M.ForecastInputs:
        keys = [key for (_i, _j, key, _f, _b) in eligible]
        values, valid, times, step_s = self.history.matrix(keys, now)
        K = len(eligible)
        horizon = np.zeros(K, np.float32)
        half_life = np.ones(K, np.float32)
        model = np.zeros(K, np.int32)
        season = np.zeros(K, np.int32)
        alpha = np.zeros(K, np.float32)
        beta = np.zeros(K, np.float32)
        gamma = np.zeros(K, np.float32)
        for k, (_i, _j, _key, fspec, _b) in enumerate(eligible):
            horizon[k] = fspec.horizon_seconds
            half_life[k] = max(float(fspec.horizon_seconds), 1.0)
            model[k] = M.MODEL_CODES.get(fspec.model, M.MODEL_LINEAR)
            if fspec.season_seconds > 0 and step_s[k] > 0:
                season[k] = int(round(fspec.season_seconds / step_s[k]))
            alpha[k] = fspec.alpha
            beta[k] = fspec.beta
            gamma[k] = fspec.gamma
        # recency decay for the linear fit, computed HOST-side (a
        # transcendental inside the kernel would break numpy parity —
        # forecast/models.py): a sample one horizon old weighs half as
        # much as the newest, so a regime change overtakes stale history
        # within a few horizons
        weights = np.power(
            np.float32(0.5), (-times) / half_life[:, None]
        ).astype(np.float32)
        return M.ForecastInputs(
            values=values, valid=valid, times=times, weights=weights,
            horizon=horizon, step_s=step_s, model=model, season=season,
            alpha=alpha, beta=beta, gamma=gamma,
        )
