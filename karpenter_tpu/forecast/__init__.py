"""Predictive scaling subsystem (docs/forecasting.md).

history.py  bounded columnar ring buffers of observed metric samples
models.py   batched Holt-Winters / robust-linear device kernels with a
            bit-identical numpy mirror
engine.py   FleetForecaster — per-tick orchestration: ingest, skill
            tracking, ONE coalesced device dispatch for the whole fleet
"""

from karpenter_tpu.forecast.engine import (
    FleetForecaster,
    REASON_SKILL_DEGRADED,
    REASON_UNAVAILABLE,
    REASON_WARMING_UP,
    query_key,
)
from karpenter_tpu.forecast.history import MetricHistoryStore
from karpenter_tpu.forecast.models import (
    ForecastInputs,
    ForecastOutputs,
    MODEL_CODES,
    MODEL_HOLT_WINTERS,
    MODEL_LINEAR,
    forecast,
    forecast_jit,
    forecast_numpy,
)

__all__ = [
    "FleetForecaster",
    "ForecastInputs",
    "ForecastOutputs",
    "MetricHistoryStore",
    "MODEL_CODES",
    "MODEL_HOLT_WINTERS",
    "MODEL_LINEAR",
    "REASON_SKILL_DEGRADED",
    "REASON_UNAVAILABLE",
    "REASON_WARMING_UP",
    "forecast",
    "forecast_jit",
    "forecast_numpy",
    "query_key",
]
