"""Metric history store: bounded columnar ring buffers per series.

The reference reads one instantaneous value per reconcile and throws it
away; forecasting needs the trajectory. This store is the retention
layer: each series (an HA's metric, or a raw client query) owns a
fixed-capacity ring of (timestamp, value) columns — appends are O(1)
array writes, snapshots are two slice copies, and memory is bounded by
construction (capacity × max_series), so a fleet of thousands of
autoscalers costs megabytes, not growth.

Keys are tuples; the two producers in the stack use:

  ("ha", namespace, name, metric_index)   BatchAutoscaler snapshot path
  ("q", metric_name, sorted-label-tuple)  metrics-client observation path

The query-keyed series double as a WARM POOL: a freshly created HA whose
query was already being observed (by another HA, or by earlier client
reads) seeds its own series from the query series instead of starting
cold (`seed`), so forecasting starts `min_samples` ticks sooner.

Lifecycle: the store lives on the runtime (it survives engine requeues
and controller deactivation/reactivation by construction); `prune`
drops every series of a deleted HorizontalAutoscaler — wired through the
HA controller's on_deleted hook. When max_series is exceeded the
least-recently-appended series is evicted, so leaked keys (renamed
queries) age out instead of accumulating.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


class _Ring:
    __slots__ = ("ts", "values", "start", "count")

    def __init__(self, capacity: int):
        # f64 timestamps: epoch seconds lose sub-second precision in f32
        self.ts = np.zeros(capacity, np.float64)
        self.values = np.zeros(capacity, np.float32)
        self.start = 0
        self.count = 0

    def append(self, t: float, value: float) -> None:
        cap = self.ts.shape[0]
        if self.count < cap:
            i = (self.start + self.count) % cap
            self.count += 1
        else:
            i = self.start
            self.start = (self.start + 1) % cap
        self.ts[i] = t
        self.values[i] = value

    def chronological(self) -> Tuple[np.ndarray, np.ndarray]:
        cap = self.ts.shape[0]
        idx = (self.start + np.arange(self.count)) % cap
        return self.ts[idx], self.values[idx]

    def last(self) -> Optional[Tuple[float, float]]:
        if self.count == 0:
            return None
        cap = self.ts.shape[0]
        i = (self.start + self.count - 1) % cap
        return float(self.ts[i]), float(self.values[i])


class MetricHistoryStore:
    """Thread-safe map of series key -> bounded ring (module docstring)."""

    def __init__(self, capacity: int = 64, max_series: int = 4096):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = capacity
        self.max_series = max_series
        self._rings: Dict[tuple, _Ring] = {}
        self._touched: Dict[tuple, float] = {}  # key -> last append time
        self._lock = threading.Lock()
        # crash safety (karpenter_tpu/recovery): a JournalHandle
        # recording appends (bounded by the ring capacity — the journal
        # fold keeps only the newest `cap` samples per key), so forecast
        # history survives a controller restart instead of cold-starting
        # every series
        self.journal = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._rings)

    def append(self, key: tuple, t: float, value: float) -> None:
        """Record one observation. Non-finite values are dropped — a NaN
        in the window would poison every downstream recurrence."""
        if not np.isfinite(value):
            return
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = _Ring(self.capacity)
                if len(self._rings) > self.max_series:
                    self._evict_oldest_locked()
            ring.append(float(t), float(value))
            self._touched[key] = float(t)
        if self.journal is not None:
            self.journal.append_sample(
                key, float(t), float(value), cap=self.capacity
            )

    def _evict_oldest_locked(self) -> None:
        victim = min(self._touched, key=self._touched.get, default=None)
        if victim is not None:
            self._rings.pop(victim, None)
            self._touched.pop(victim, None)

    def series(self, key: tuple) -> Tuple[np.ndarray, np.ndarray]:
        """(timestamps f64, values f32) in chronological order; empty
        arrays for an unknown key."""
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                return np.zeros(0, np.float64), np.zeros(0, np.float32)
            return ring.chronological()

    def last(self, key: tuple) -> Optional[Tuple[float, float]]:
        """Newest (timestamp, value), or None."""
        with self._lock:
            ring = self._rings.get(key)
            return None if ring is None else ring.last()

    def count(self, key: tuple) -> int:
        with self._lock:
            ring = self._rings.get(key)
            return 0 if ring is None else ring.count

    def seed(self, key: tuple, from_key: tuple) -> bool:
        """Copy `from_key`'s ring into an EMPTY `key` (the warm-pool
        path for a fresh HA over an already-observed query)."""
        with self._lock:
            if self._rings.get(key) is not None:
                return False
            src = self._rings.get(from_key)
            if src is None or src.count == 0:
                return False
            ring = _Ring(self.capacity)
            ts, vs = src.chronological()
            for t, v in zip(ts, vs):
                ring.append(float(t), float(v))
            self._rings[key] = ring
            self._touched[key] = self._touched.get(from_key, float(ts[-1]))
            if len(self._rings) > self.max_series:
                # same bound append() enforces: seeding must not grow
                # the store past capacity x max_series
                self._evict_oldest_locked()
            return True

    def prune(self, *prefix) -> int:
        """Drop every series whose key starts with `prefix` (e.g.
        prune("ha", namespace, name) on HA deletion); returns the count
        dropped."""
        with self._lock:
            victims = [
                k for k in self._rings if k[: len(prefix)] == tuple(prefix)
            ]
            for k in victims:
                del self._rings[k]
                self._touched.pop(k, None)
        if self.journal is not None:
            for k in victims:
                self.journal.delete(k)
        return len(victims)

    # -- crash-safe snapshot/restore (karpenter_tpu/recovery) --------------

    def snapshot_rings(self) -> Dict[str, list]:
        """Columnar checkpoint of every ring: {key_str: [[t, v], ...]}
        in chronological order — the recovery checkpoint format (the
        journal fold produces the same shape from appends)."""
        from karpenter_tpu.recovery.journal import key_str

        with self._lock:
            items = [
                (key, ring.chronological())
                for key, ring in self._rings.items()
            ]
        return {
            key_str(key): [
                [float(t), float(v)] for t, v in zip(ts, vs)
            ]
            for key, (ts, vs) in items
        }

    def restore_ring(self, key: tuple, samples: list) -> None:
        """Rebuild one series from replayed [t, value] samples WITHOUT
        re-journaling them (the caller just read them from the journal)."""
        if not samples:
            return
        with self._lock:
            ring = _Ring(self.capacity)
            for t, v in samples[-self.capacity:]:
                ring.append(float(t), float(v))
            self._rings[key] = ring
            self._touched[key] = float(samples[-1][0])
            if len(self._rings) > self.max_series:
                self._evict_oldest_locked()

    # -- batched snapshot --------------------------------------------------

    def matrix(
        self, keys: List[tuple], now: float, length: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Right-aligned [K, L] snapshot of `keys` for the batched
        forecaster: (values f32, valid bool, times f32 relative to
        `now`, step_s f32 mean spacing). L defaults to the ring
        capacity; series shorter than L are left-padded invalid — the
        layout forecast/models.py is specified against."""
        L = self.capacity if length is None else length
        K = len(keys)
        values = np.zeros((K, L), np.float32)
        valid = np.zeros((K, L), bool)
        times = np.zeros((K, L), np.float32)
        step_s = np.zeros(K, np.float32)
        for i, key in enumerate(keys):
            ts, vs = self.series(key)
            n = min(len(vs), L)
            if n == 0:
                continue
            ts, vs = ts[-n:], vs[-n:]
            values[i, L - n:] = vs
            valid[i, L - n:] = True
            times[i, L - n:] = (ts - float(now)).astype(np.float32)
            if n >= 2:
                step_s[i] = np.float32((ts[-1] - ts[0]) / (n - 1))
        return values, valid, times, step_s
