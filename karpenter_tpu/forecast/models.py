"""Batched on-device forecasting models: every HA series in ONE dispatch.

The reference autoscaler is purely reactive — one instantaneous PromQL
value per reconcile — so a TPU node-group ramp is always chased from
behind by the full node-provisioning latency. This module is the math
half of the predictive subsystem (docs/forecasting.md): given the fleet's
metric histories as ONE [S, T] matrix, produce a point forecast at each
series' horizon as ONE array program. Two models, selected per series by
an i32 code so the whole fleet rides a single compiled program:

  MODEL_LINEAR       robust linear trend: an OLS fit over (time, value)
                     re-weighted once by Huber-style weights on the OLS
                     residuals (one IRLS round), projected `horizon`
                     seconds past the newest sample. Robust to the step
                     outliers a flaky exporter or a deploy blip writes
                     into the window.
  MODEL_HOLT_WINTERS additive Holt-Winters: level + trend + a seasonal
                     buffer of `season` sample slots (season < 2 runs
                     plain Holt — level/trend only). Smoothing factors
                     alpha/beta/gamma ride per series.

Parity contract (pinned bit-for-bit by tests/test_forecast.py): the
jitted kernel and `forecast_numpy` produce IDENTICAL f32 bits. Float
parity across XLA and numpy is only achievable by construction, so the
kernel obeys two rules mirrored exactly on the host:

  * every multiply-accumulate is written in single-mul form
    (`a * b + c`, the lerp form `c + a*(x - c)` for smoothing updates):
    XLA:CPU contracts exactly that shape into one FMA, which the numpy
    mirror reproduces with a float64 round-trip
    (`f32(f64(a)*f64(b) + f64(c))` — the product is exact in f64, so
    the round-trip equals the fused single rounding);
  * every reduction over time is a SEQUENTIAL scan (lax.scan on device,
    an explicit loop on host) — never jnp.sum/np.sum, whose pairwise
    orders differ.

Histories are RIGHT-ALIGNED: the newest sample sits at column T-1 and
shorter series are left-padded with valid=False (the mask, not the
padding, decides what the recurrences see), so shape-bucketing the T
axis never perturbs results.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

MODEL_LINEAR = 0
MODEL_HOLT_WINTERS = 1

MODEL_CODES = {
    "linear": MODEL_LINEAR,
    "holt-winters": MODEL_HOLT_WINTERS,
}

_ONE = np.float32(1.0)
_ZERO = np.float32(0.0)
# Huber-style reweighting threshold, in units of the OLS residual RMS:
# residuals inside the tube keep weight 1, outliers decay as k/|r|.
_HUBER_K = np.float32(1.5)
# guard for per-step horizon conversion: a degenerate (single-sample or
# zero-spacing) series must not divide by zero
_MIN_STEP_S = np.float32(1e-3)


@jax.tree_util.register_dataclass
@dataclass
class ForecastInputs:
    """Structure-of-arrays snapshot of every forecastable metric series.

    All arrays are host numpy (the service device_puts on dispatch);
    shapes are [S, T] / [S] with S = series and T = history slots.
    """

    values: jax.Array  # f32[S, T] observed values, right-aligned
    valid: jax.Array  # bool[S, T] sample-present mask
    times: jax.Array  # f32[S, T] seconds relative to now (<= 0)
    # base regression weights (linear model only) — recency decay is
    # computed on the HOST (engine.py) and enters as data, because a
    # transcendental (exp/pow) inside the kernel would break the
    # bit-parity contract between XLA and the numpy mirror
    weights: jax.Array  # f32[S, T]
    horizon: jax.Array  # f32[S] forecast horizon seconds (> 0)
    step_s: jax.Array  # f32[S] mean sample spacing seconds
    model: jax.Array  # i32[S] MODEL_* code
    season: jax.Array  # i32[S] Holt-Winters season length in SAMPLES
    alpha: jax.Array  # f32[S] level smoothing
    beta: jax.Array  # f32[S] trend smoothing
    gamma: jax.Array  # f32[S] seasonal smoothing


@jax.tree_util.register_dataclass
@dataclass
class ForecastOutputs:
    point: jax.Array  # f32[S] forecast value `horizon` seconds ahead
    sigma2: jax.Array  # f32[S] robust residual variance (fit quality)
    n_valid: jax.Array  # i32[S] samples the fit actually saw


# -- device kernel ------------------------------------------------------------


def _hw_scan(inputs: ForecastInputs):
    """Masked Holt-Winters recurrence over the T axis; returns final
    (level, trend, seasonal buffer, valid-step count)."""
    S, T = inputs.values.shape
    # effective season length, clamped to the buffer (a season longer
    # than the retained history cannot be estimated anyway)
    m = jnp.clip(inputs.season, 1, T)  # [S]
    seasonal_on = (inputs.season >= 2)[:, None]  # [S, 1]
    slots = jnp.arange(T, dtype=jnp.int32)[None, :]  # [1, T]

    def step(carry, xt):
        level, trend, seas, cnt, seen = carry
        x, v = xt
        idx = jnp.mod(cnt, m)  # [S] current seasonal slot
        s_old = jnp.where(
            seasonal_on,
            jnp.take_along_axis(seas, idx[:, None], axis=1),
            _ZERO,
        )[:, 0]
        init = v & ~seen
        # single-mul lerp forms (module docstring: the FMA contract)
        q = level + trend
        nl = inputs.alpha * ((x - s_old) - q) + q
        nt = inputs.beta * ((nl - level) - trend) + trend
        ns = inputs.gamma * ((x - nl) - s_old) + s_old
        level2 = jnp.where(init, x, jnp.where(v, nl, level))
        trend2 = jnp.where(init, _ZERO, jnp.where(v, nt, trend))
        write = (slots == idx[:, None]) & v[:, None] & seasonal_on
        seas2 = jnp.where(write, ns[:, None], seas)
        cnt2 = jnp.where(v, cnt + 1, cnt)
        return (level2, trend2, seas2, cnt2, seen | v), None

    z = jnp.zeros(S, jnp.float32)
    carry0 = (
        z, z, jnp.zeros((S, T), jnp.float32),
        jnp.zeros(S, jnp.int32), jnp.zeros(S, bool),
    )
    (level, trend, seas, cnt, _), _ = jax.lax.scan(
        step, carry0, (inputs.values.T, inputs.valid.T)
    )
    return level, trend, seas, cnt


def _linear_sums(values, valid, times, weights):
    """Sequentially accumulated weighted regression sums (FMA forms)."""
    S = values.shape[0]

    def step(carry, xt):
        sw, st, sv, stt, stv = carry
        x, v, t, w0 = xt
        w = jnp.where(v, w0, _ZERO)
        wt = w * t
        return (
            sw + w,
            wt + st,
            w * x + sv,
            wt * t + stt,
            wt * x + stv,
        ), None

    z = jnp.zeros(S, jnp.float32)
    (sw, st, sv, stt, stv), _ = jax.lax.scan(
        step, (z, z, z, z, z),
        (values.T, valid.T, times.T, weights.T),
    )
    return sw, st, sv, stt, stv


def _linear_fit(values, valid, times, weights):
    """Weighted least squares of value on time; returns (slope,
    intercept-at-t=0, sw). Degenerate fits (fewer than 2 points, zero
    time spread) collapse to slope 0 through the `den` guard."""
    sw, st, sv, stt, stv = _linear_sums(values, valid, times, weights)
    den = sw * stt + -(st * st)
    num = sw * stv + -(st * sv)
    ok = den > 0
    slope = jnp.where(ok, num / jnp.where(ok, den, _ONE), _ZERO)
    sw_safe = jnp.where(sw > 0, sw, _ONE)
    mean_t = st / sw_safe
    mean_v = sv / sw_safe
    intercept = -slope * mean_t + mean_v
    return slope, intercept, sw


def _residual_stats(values, valid, times, weights, slope, intercept):
    """Weighted (sum of squared residuals, sum of weights) — sequential."""
    S = values.shape[0]

    def step(carry, xt):
        sse, sw = carry
        x, v, t, w0 = xt
        w = jnp.where(v, w0, _ZERO)
        r = x - (slope * t + intercept)
        wr = w * r
        return (wr * r + sse, sw + w), None

    z = jnp.zeros(S, jnp.float32)
    (sse, sw), _ = jax.lax.scan(
        step, (z, z), (values.T, valid.T, times.T, weights.T)
    )
    return sse, sw


def forecast(inputs: ForecastInputs) -> ForecastOutputs:
    """The batched forecast program (see module docstring)."""
    values, valid, times = inputs.values, inputs.valid, inputs.times
    base = inputs.weights

    # --- robust linear: WLS -> residual scale -> one Huber reweight ---
    slope0, icept0, _ = _linear_fit(values, valid, times, base)
    sse0, sw0 = _residual_stats(values, valid, times, base, slope0, icept0)
    sw0_safe = jnp.where(sw0 > 0, sw0, _ONE)
    scale2 = sse0 / sw0_safe  # residual mean square (variance proxy)
    # w = min(1, k*scale/|r|) without sqrt: w^2 = min(1, k^2*scale2/r^2),
    # applied as w2 directly (a monotone reweighting with the same
    # outlier-downweighting shape; keeps the kernel sqrt-free)
    r = values - (slope0[:, None] * times + icept0[:, None])
    r2 = r * r
    k2s = (_HUBER_K * _HUBER_K) * scale2
    w_rob = base * jnp.where(r2 > k2s[:, None], k2s[:, None] / jnp.where(
        r2 > 0, r2, _ONE
    ), _ONE)
    slope, icept, _ = _linear_fit(values, valid, times, w_rob)
    sse, swr = _residual_stats(values, valid, times, w_rob, slope, icept)
    sigma2_lin = sse / jnp.where(swr > 0, swr, _ONE)
    point_lin = slope * inputs.horizon + icept

    # --- Holt-Winters ---
    level, trend, seas, cnt = _hw_scan(inputs)
    step_s = jnp.maximum(inputs.step_s, _MIN_STEP_S)
    h_steps = inputs.horizon / step_s
    point_hw = trend * h_steps + level
    m = jnp.clip(inputs.season, 1, values.shape[1])
    seasonal_on = inputs.season >= 2
    # phase of the forecast target: the newest sample sat at phase
    # (cnt-1) mod m; the target sits round(h_steps) later
    h_i = jnp.round(h_steps).astype(jnp.int32)
    idx_f = jnp.mod(jnp.maximum(cnt - 1, 0) + h_i, m)
    seas_at = jnp.where(
        seasonal_on,
        jnp.take_along_axis(seas, idx_f[:, None], axis=1)[:, 0],
        _ZERO,
    )
    point_hw = point_hw + seas_at

    n_valid = cnt
    is_hw = inputs.model == MODEL_HOLT_WINTERS
    point = jnp.where(is_hw, point_hw, point_lin)
    # both models report the robust linear residual variance as the fit-
    # quality signal (a dedicated HW one-step-ahead error scan would
    # double the program for a gauge-only output)
    sigma2 = sigma2_lin
    # a series with no samples forecasts 0 with infinite-variance
    # semantics left to the caller (n_valid carries the evidence count)
    point = jnp.where(n_valid > 0, point, _ZERO)
    return ForecastOutputs(
        point=point, sigma2=sigma2, n_valid=n_valid.astype(jnp.int32)
    )


forecast_jit = jax.jit(forecast)


# -- shape plumbing for the solve service -------------------------------------
# Padding is semantics-preserving by construction: extra T slots are
# left-padded valid=False (the recurrences carry state through masked
# steps unchanged and masked regression terms add exact zeros), and
# extra S rows are fully invalid, per-series independent, and sliced off
# before results scatter back — so bucketed outputs EQUAL unbucketed
# ones bit for bit (the same argument solver/bucketing.py makes).


def pad_forecast_inputs(inputs: ForecastInputs, t_pad: int) -> ForecastInputs:
    """Left-pad the time axis to `t_pad` slots (right-alignment keeps
    the newest sample at T-1). Returns `inputs` unchanged when already
    there."""
    t = np.asarray(inputs.values).shape[1]
    if t == t_pad:
        return inputs
    if t > t_pad:
        raise ValueError(f"history length {t} exceeds bucket {t_pad}")

    def left(a, fill=0):
        a = np.asarray(a)
        out = np.full((a.shape[0], t_pad), fill, a.dtype)
        out[:, t_pad - t:] = a
        return out

    return ForecastInputs(
        values=left(inputs.values),
        valid=left(inputs.valid, False),
        times=left(inputs.times),
        weights=left(inputs.weights),
        horizon=np.asarray(inputs.horizon),
        step_s=np.asarray(inputs.step_s),
        model=np.asarray(inputs.model),
        season=np.asarray(inputs.season),
        alpha=np.asarray(inputs.alpha),
        beta=np.asarray(inputs.beta),
        gamma=np.asarray(inputs.gamma),
    )


def concat_forecast_inputs(
    padded: List["ForecastInputs"], s_pad: int
) -> ForecastInputs:
    """Stack same-T requests along the series axis and bottom-pad with
    all-invalid rows to `s_pad` (the coalesced-dispatch stack)."""
    import dataclasses

    total = sum(np.asarray(p.values).shape[0] for p in padded)
    extra = s_pad - total

    def cat(name: str, fill=0):
        parts = [np.asarray(getattr(p, name)) for p in padded]
        out = np.concatenate(parts, axis=0)
        if extra > 0:
            pad_shape = (extra,) + out.shape[1:]
            out = np.concatenate(
                [out, np.full(pad_shape, fill, out.dtype)], axis=0
            )
        return out

    return ForecastInputs(
        **{
            f.name: cat(f.name, False if f.name == "valid" else 0)
            for f in dataclasses.fields(ForecastInputs)
        }
    )


def slice_forecast_outputs(out, start: int, stop: int) -> ForecastOutputs:
    """One request's rows out of a coalesced dispatch's host outputs."""
    return ForecastOutputs(
        point=np.asarray(out.point)[start:stop],
        sigma2=np.asarray(out.sigma2)[start:stop],
        n_valid=np.asarray(out.n_valid)[start:stop],
    )


# -- numpy mirror -------------------------------------------------------------
# The degradation target (service numpy fallback) AND the parity oracle.
# Every line mirrors the kernel's op order; _fma reproduces XLA:CPU's
# mul-add contraction exactly (module docstring).


def _fma(a, b, c):
    return (
        np.asarray(a, np.float64) * np.asarray(b, np.float64)
        + np.asarray(c, np.float64)
    ).astype(np.float32)


def _np_hw_scan(inputs: ForecastInputs):
    values = np.asarray(inputs.values, np.float32)
    valid = np.asarray(inputs.valid, bool)
    S, T = values.shape
    m = np.clip(np.asarray(inputs.season, np.int32), 1, T)
    seasonal_on = np.asarray(inputs.season, np.int32) >= 2
    alpha = np.asarray(inputs.alpha, np.float32)
    beta = np.asarray(inputs.beta, np.float32)
    gamma = np.asarray(inputs.gamma, np.float32)

    level = np.zeros(S, np.float32)
    trend = np.zeros(S, np.float32)
    seas = np.zeros((S, T), np.float32)
    cnt = np.zeros(S, np.int32)
    seen = np.zeros(S, bool)
    rows = np.arange(S)
    for t in range(T):
        x, v = values[:, t], valid[:, t]
        idx = np.mod(cnt, m)
        s_old = np.where(seasonal_on, seas[rows, idx], _ZERO)
        init = v & ~seen
        q = level + trend
        nl = _fma(alpha, (x - s_old) - q, q)
        nt = _fma(beta, (nl - level) - trend, trend)
        ns = _fma(gamma, (x - nl) - s_old, s_old)
        level = np.where(init, x, np.where(v, nl, level)).astype(np.float32)
        trend = np.where(init, _ZERO, np.where(v, nt, trend)).astype(
            np.float32
        )
        write = v & seasonal_on
        seas[rows[write], idx[write]] = ns[write]
        cnt = np.where(v, cnt + 1, cnt).astype(np.int32)
        seen |= v
    return level, trend, seas, cnt


def _np_linear_fit(values, valid, times, weights):
    S, T = values.shape
    z = np.zeros(S, np.float32)
    sw, st, sv, stt, stv = z.copy(), z.copy(), z.copy(), z.copy(), z.copy()
    for t in range(T):
        x, v, tt, w0 = values[:, t], valid[:, t], times[:, t], weights[:, t]
        w = np.where(v, w0, _ZERO).astype(np.float32)
        wt = w * tt
        sw = sw + w
        st = _fma(w, tt, st)
        sv = _fma(w, x, sv)
        stt = _fma(wt, tt, stt)
        stv = _fma(wt, x, stv)
    den = _fma(sw, stt, -(st * st))
    num = _fma(sw, stv, -(st * sv))
    ok = den > 0
    slope = np.where(ok, num / np.where(ok, den, _ONE), _ZERO).astype(
        np.float32
    )
    sw_safe = np.where(sw > 0, sw, _ONE).astype(np.float32)
    mean_t = st / sw_safe
    mean_v = sv / sw_safe
    intercept = _fma(-slope, mean_t, mean_v)
    return slope, intercept, sw


def _np_residual_stats(values, valid, times, weights, slope, intercept):
    S, T = values.shape
    sse, sw = np.zeros(S, np.float32), np.zeros(S, np.float32)
    for t in range(T):
        x, v, tt, w0 = values[:, t], valid[:, t], times[:, t], weights[:, t]
        w = np.where(v, w0, _ZERO).astype(np.float32)
        r = x - _fma(slope, tt, intercept)
        wr = w * r
        sse = _fma(wr, r, sse)
        sw = sw + w
    return sse, sw


def forecast_numpy(inputs: ForecastInputs) -> ForecastOutputs:
    """Host mirror of forecast() — the numpy degradation path. Produces
    bit-identical f32 outputs (module docstring parity contract)."""
    values = np.asarray(inputs.values, np.float32)
    valid = np.asarray(inputs.valid, bool)
    times = np.asarray(inputs.times, np.float32)
    horizon = np.asarray(inputs.horizon, np.float32)
    base = np.asarray(inputs.weights, np.float32)

    slope0, icept0, _ = _np_linear_fit(values, valid, times, base)
    sse0, sw0 = _np_residual_stats(
        values, valid, times, base, slope0, icept0
    )
    sw0_safe = np.where(sw0 > 0, sw0, _ONE).astype(np.float32)
    scale2 = sse0 / sw0_safe
    r = values - _fma(
        slope0[:, None], times, np.broadcast_to(icept0[:, None], values.shape)
    )
    r2 = r * r
    k2s = (_HUBER_K * _HUBER_K) * scale2
    w_rob = base * np.where(
        r2 > k2s[:, None],
        k2s[:, None] / np.where(r2 > 0, r2, _ONE),
        _ONE,
    ).astype(np.float32)
    slope, icept, _ = _np_linear_fit(values, valid, times, w_rob)
    sse, swr = _np_residual_stats(values, valid, times, w_rob, slope, icept)
    sigma2_lin = (sse / np.where(swr > 0, swr, _ONE)).astype(np.float32)
    point_lin = _fma(slope, horizon, icept)

    level, trend, seas, cnt = _np_hw_scan(inputs)
    step_s = np.maximum(np.asarray(inputs.step_s, np.float32), _MIN_STEP_S)
    h_steps = (horizon / step_s).astype(np.float32)
    point_hw = _fma(trend, h_steps, level)
    m = np.clip(np.asarray(inputs.season, np.int32), 1, values.shape[1])
    seasonal_on = np.asarray(inputs.season, np.int32) >= 2
    h_i = np.round(h_steps).astype(np.int32)
    idx_f = np.mod(np.maximum(cnt - 1, 0) + h_i, m)
    rows = np.arange(values.shape[0])
    seas_at = np.where(seasonal_on, seas[rows, idx_f], _ZERO)
    point_hw = point_hw + seas_at

    is_hw = np.asarray(inputs.model, np.int32) == MODEL_HOLT_WINTERS
    point = np.where(is_hw, point_hw, point_lin).astype(np.float32)
    sigma2 = sigma2_lin
    point = np.where(cnt > 0, point, _ZERO).astype(np.float32)
    return ForecastOutputs(
        point=point, sigma2=sigma2, n_valid=cnt.astype(np.int32)
    )
