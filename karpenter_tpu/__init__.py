"""karpenter_tpu: a TPU-native, metrics-driven node-autoscaling framework.

Capabilities-equivalent rebuild of early Karpenter (awslabs/karpenter v0.1.x,
reference at /root/reference): MetricsProducers emit scaling signals, an
HPA-compatible HorizontalAutoscaler turns signals into desired replicas, and
ScalableNodeGroups actuate replicas through a pluggable cloud-provider
boundary. Unlike the reference's one-scalar-decision-per-object-per-tick Go
control plane, the decision path here is a batched JAX/XLA array program: all
autoscalers, pending pods, and node groups are evaluated as one vectorized
constraint problem on TPU.
"""

__version__ = "0.1.0"
