# Build system (reference: Makefile — dev/ci/test/battletest/verify/codegen).
PYTHON ?= python

help: ## Display help
	@grep -E '^[a-zA-Z_-]+:.*## ' $(MAKEFILE_LIST) | awk -F':.*## ' '{printf "%-12s %s\n", $$1, $$2}'

dev: codegen verify test ## Codegen, lint, test — the inner loop

ci: codegen verify battletest ## Everything the gate runs

test: ## Run the test suite (virtual 8-device CPU mesh)
	$(PYTHON) -m pytest tests/ -x -q

battletest: ## Randomized order + scale + stress + coverage when available (reference: Makefile battletest)
	@# coverage is opportunistic but NEVER silent: the gate says which
	@# mode it runs in, and a failing test fails it in either mode
	@if $(PYTHON) -c "import pytest_cov" 2>/dev/null; then \
		echo "battletest: with coverage"; \
		KARPENTER_TEST_SHUFFLE=random KARPENTER_SCALE_TESTS=1 $(PYTHON) -m pytest tests/ -q --cov=karpenter_tpu --cov-report=term-missing; \
	else \
		echo "battletest: pytest-cov not installed, running WITHOUT coverage"; \
		KARPENTER_TEST_SHUFFLE=random KARPENTER_SCALE_TESTS=1 $(PYTHON) -m pytest tests/ -q; \
	fi

verify: ## Static checks: compile, import, AST lint (complexity bound + unused imports)
	$(PYTHON) -m compileall -q karpenter_tpu tests hack bench.py __graft_entry__.py
	$(PYTHON) -c "import karpenter_tpu"
	$(PYTHON) hack/lint.py

codegen: ## Regenerate config/crd/*.yaml + releases/manifest.yaml from the API types
	bash hack/release.sh

docs: ## Generate docs/API.md from the API types (reference: Makefile docs target)
	$(PYTHON) -m karpenter_tpu.codegen --docs docs/API.md

native: ## Pre-build the C accelerators (otherwise built lazily in background)
	$(PYTHON) -c "from karpenter_tpu.native import load_kquantity; \
		assert load_kquantity() is not None, 'native build failed'; print('native ok')"

bench: ## Headline benchmark (runs on the real TPU when present)
	$(PYTHON) bench.py

dryrun: ## Multi-chip sharding compile check on 8 virtual CPU devices
	$(PYTHON) -c "import os; \
		os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS','') + ' --xla_force_host_platform_device_count=8').strip(); \
		import jax; jax.config.update('jax_platforms', 'cpu'); \
		import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

.PHONY: help dev ci test battletest verify codegen docs native bench dryrun
