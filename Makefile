# Build system (reference: Makefile — dev/ci/test/battletest/verify/codegen,
# plus the ko-based publish/apply flow the image targets mirror).
PYTHON ?= python
# Container engine + image coordinates (reference: KO_DOCKER_REPO/RELEASE_REPO)
ENGINE ?= $(shell command -v docker || command -v podman)
IMAGE_REPO ?= karpenter-tpu
IMAGE_TAG ?= latest
IMAGE = $(IMAGE_REPO):$(IMAGE_TAG)
JAX_EXTRAS ?= tpu

help: ## Display help
	@grep -E '^[a-zA-Z_-]+:.*## ' $(MAKEFILE_LIST) | awk -F':.*## ' '{printf "%-12s %s\n", $$1, $$2}'

dev: codegen verify test ## Codegen, lint, test — the inner loop

ci: codegen verify battletest ## Everything the gate runs

test: ## Run the test suite (virtual 8-device CPU mesh)
	$(PYTHON) -m pytest tests/ -x -q
	@echo "note: ~300 skips are the battletest-gated tiers (fuzz sweep," \
		"scale/stress, real-backend/apiserver) — 'make battletest' or" \
		"'make ci' runs them"

test-chaos: ## Seeded chaos suite: runtime + solver under injected faults (docs/resilience.md)
	$(PYTHON) -m pytest tests/test_faults.py tests/test_chaos.py -q

test-recovery: ## Seeded kill-and-restart suite: crash-safe state, fencing, warm-up (docs/resilience.md "Crash recovery")
	$(PYTHON) -m pytest tests/test_recovery.py tests/test_restart_chaos.py -q

test-failover: ## Replicated control plane: leader-kill handoff, exactly-once actuation, split-brain fencing (docs/resilience.md "Replicated control plane")
	$(PYTHON) -m pytest tests/test_failover.py -q

battletest: ## Randomized order + scale + stress + coverage when available (reference: Makefile battletest)
	@# coverage is opportunistic but NEVER silent: the gate says which
	@# mode it runs in, and a failing test fails it in either mode
	@if $(PYTHON) -c "import pytest_cov" 2>/dev/null; then \
		echo "battletest: with coverage"; \
		KARPENTER_TEST_SHUFFLE=random KARPENTER_SCALE_TESTS=1 $(PYTHON) -m pytest tests/ -q --cov=karpenter_tpu --cov-report=term-missing; \
	else \
		echo "battletest: pytest-cov not installed, running WITHOUT coverage"; \
		KARPENTER_TEST_SHUFFLE=random KARPENTER_SCALE_TESTS=1 $(PYTHON) -m pytest tests/ -q; \
	fi

verify: ## Static checks: compile, import, AST lint (complexity bound + unused imports)
	$(PYTHON) -m compileall -q karpenter_tpu tests hack bench.py __graft_entry__.py
	$(PYTHON) -c "import karpenter_tpu"
	$(PYTHON) hack/lint.py

codegen: ## Regenerate config/crd/*.yaml + releases/manifest.yaml from the API types
	bash hack/release.sh

docs: ## Generate docs/API.md from the API types (reference: Makefile docs target)
	$(PYTHON) -m karpenter_tpu.codegen --docs docs/API.md

native: ## Pre-build the C accelerators (otherwise built lazily in background)
	$(PYTHON) -c "from karpenter_tpu.native import load_kquantity; \
		assert load_kquantity() is not None, 'native build failed'; print('native ok')"

bench: ## Headline benchmark (runs on the real TPU when present)
	$(PYTHON) bench.py

bench-solver: ## Direct vs coalesced solver-service p50/p99 (10k pods x 50 types); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --solver-service --pods 10000 --types 50 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-hotpath: ## Idle-queue service vs direct p50 + per-stage breakdown (10k pods x 50 types); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --hotpath --pods 10000 --types 50 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-consolidate: ## Batched vs sequential drain-candidate evaluation (32 candidates x 480 bound pods); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --consolidate --candidates 32 --pods 480 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-forecast: ## Batched one-dispatch fleet forecast vs per-series loop (512 series x 64 samples); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --forecast --series 512 --history 64 \
		--iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-preempt: ## Batched one-dispatch eviction planning vs per-candidate loop (32 candidates x 50 node columns x 10k victims); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --preempt --candidates 32 --types 50 \
		--pods 10000 --backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-cost: ## Batched multi-objective cost/SLO refine vs per-HA sequential loop (512 autoscalers x 3 metrics, numpy parity pinned); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --cost --cost-rows 512 --cost-metrics 3 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-poolgroup: ## One batched joint pool-group dispatch vs the groups*pools per-pool cost dispatches it replaces (64 groups x 4 pools, numpy + cost-ladder parity pinned); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --poolgroup --poolgroup-groups 64 \
		--poolgroup-pools 4 --poolgroup-metrics 3 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-journal: ## Protective-state journal overhead on the reconcile hot path (target <5% tick-latency regression); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --journal --journal-ticks 40 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-trace: ## Reconcile-tracing overhead on the hot path: tracer enabled vs disabled, interleaved (target <5% tick-latency regression); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --trace --trace-ticks 200 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-provenance: ## Decision-provenance ledger overhead on the reconcile hot path: ledger enabled vs disabled, interleaved over the shared churn world (target <=5% tick-latency regression); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --provenance --provenance-ticks 200 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-resident: ## Device-resident fleet state: churn-tick solve with resident scatter ON vs full re-upload OFF over one watch-fed world (shipped + forced-scatter arms, unchanged-tick column, parity pinned every tick); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --resident --pods 10000 --types 50 \
		--backend xla --resident-ticks 60 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-shard: ## Sharded fleet-scale solve (1M pods x 1k types through the SolverService seam on an 8-device mesh, 1/2/4/8 scaling + parity pins); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --shard --pods 1000000 --types 1000 \
		--backend xla --iters 3 --shard-scaling 1,2,4,8 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-multitenant: ## Aggregate decisions/sec at 1k simulated tenants: cross-tenant concatenated decide+cost vs a sequential per-tenant loop (concat == independent parity pinned on device + numpy paths); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --multitenant --tenants 1000 --tenant-rows 4 \
		--backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-eventloop: ## Event-driven reconcile: one seeded pod-arrival trace replayed tick-paced vs event-driven (e2e p50/p99 off karpenter_reconcile_e2e_seconds, solve amplification, 1k-event churn-storm coalescing); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --eventloop --eventloop-ticks 40 \
		--eventloop-arrivals 60 --eventloop-storm 1000 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-introspect: ## Solver introspection-plane overhead on the reconcile hot path: compile ledger + device telemetry + XLA cost attribution enabled vs disabled, interleaved over the shared churn world (target <=2% tick-latency regression); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --introspect --introspect-ticks 200 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-constraints: ## Batched constrained solve (spread + reservation + anti-affinity + compact groups as masked integer operands, ONE dispatch) vs the per-group sequential loop, interleaved arms, parity pinned; appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --constraints --pods 20000 --types 48 \
		--constraint-groups 8 --backend xla --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

test-simlab: ## SimLab non-slow suite: gym/batched parity pins, scenario fuzz, policy search, catalog drift lint (docs/simulator.md)
	$(PYTHON) -m pytest tests/test_simlab.py -q

bench-simlab: ## SimLab batched cluster stepping: N seeded clusters as ONE vmapped sim_rollout dispatch vs the per-cluster sequential loop (batched == sequential == numpy pinned bitwise before timing); appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --simlab --simlab-clusters 256 \
		--simlab-ticks 64 --simlab-rows 8 --iters 10 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-fusedtick: ## Fused steady-state tick: the fleet batch's forecast -> decide -> cost ladder as ONE compiled program (--fused-tick) vs the chained per-stage wire (fused == chained == numpy pinned bitwise before timing), plus the dispatches-per-tick collapse over the shared churn world; appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --fusedtick --fusedtick-rows 256 \
		--fusedtick-metrics 3 --fusedtick-series 128 \
		--fusedtick-samples 32 --fusedtick-ticks 40 --iters 20 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

bench-failover: ## Replicated-control-plane leader kill at fleet scale (256 tenants x 4 replicas): handoff blackout p99 + exactly-once audit; appends a BENCHMARKS row + publishes to BASELINE.json
	$(PYTHON) bench.py --failover --failover-tenants 256 \
		--failover-replicas 4 --failover-partitions 16 \
		--failover-ticks 40 \
		--publish-baseline --append-benchmarks docs/BENCHMARKS.md

dryrun: ## Multi-chip sharding compile check on 8 virtual CPU devices
	$(PYTHON) -c "import os; \
		os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS','') + ' --xla_force_host_platform_device_count=8').strip(); \
		import jax; jax.config.update('jax_platforms', 'cpu'); \
		import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

image: ## Build the controller+solver OCI image (reference: ko publish --local)
	@test -n "$(ENGINE)" || { echo "no docker/podman found; install one or set ENGINE="; exit 1; }
	$(ENGINE) build --build-arg JAX_EXTRAS=$(JAX_EXTRAS) -t $(IMAGE) .

publish: image ## Push the image to IMAGE_REPO (reference: Makefile publish via ko)
	$(ENGINE) push $(IMAGE)

apply: image ## Build/push the image and apply config/ with it (reference: Makefile apply via ko resolve)
	@# registry-qualified repos (contain a /) are pushed like ko does;
	@# bare local names (the kind/kind-load path) are not pushable
	@if echo "$(IMAGE_REPO)" | grep -q /; then $(ENGINE) push $(IMAGE); fi
	kubectl kustomize config/ | sed "s|karpenter-tpu:latest|$(IMAGE)|g" | kubectl apply -f -

delete: ## Remove the applied resources (reference: Makefile delete)
	kubectl kustomize config/ | kubectl delete --ignore-not-found -f -

kind-load: image ## Side-load the image into a kind cluster (no registry needed)
	@# `kind load docker-image` reads the DOCKER daemon; podman builds
	@# need the archive path
	@case "$(notdir $(ENGINE))" in \
	  docker) kind load docker-image $(IMAGE) ;; \
	  *) $(ENGINE) save $(IMAGE) | kind load image-archive /dev/stdin ;; \
	esac

conformance: ## Run the real-apiserver tier against a kind-booted apiserver (the envtest analog)
	bash hack/conformance-kind.sh

kind-smoke: ## Deploy smoke on kind: image -> apply -> pod Ready -> one HA end to end
	bash hack/kind-smoke.sh

.PHONY: help dev ci test test-chaos test-recovery test-failover battletest verify codegen \
	docs native bench bench-solver bench-hotpath bench-consolidate \
	bench-forecast bench-preempt bench-cost bench-journal bench-trace \
	bench-provenance bench-resident bench-shard bench-multitenant \
	bench-eventloop bench-introspect bench-constraints test-simlab \
	bench-simlab bench-fusedtick bench-failover bench-poolgroup dryrun \
	image publish apply delete kind-load conformance kind-smoke
