"""Consolidation subsystem (karpenter_tpu/consolidation + the solver
service's `consolidate` seam).

The acceptance pins:

  * the batched verdict for N candidates is element-for-element
    identical to N independent masked bin-packs, on the device (xla)
    path AND the numpy fallback path, and the two paths agree
    bit-identically with each other;
  * all same-bucket candidates of one consolidate() call ride ONE
    device dispatch, and candidate-count jitter inside a batch rung
    causes zero recompiles;
  * the safety layer: do-not-disrupt, cooldown, per-group budgets, and
    the cordon -> verify -> drain state machine with actuation through
    the ScalableNodeGroup controller;
  * the controller's structured scale-down-while-unstable condition.
"""

import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
    Toleration,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    FAKE_NODE_GROUP,
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.consolidation import (
    DO_NOT_DISRUPT,
    build_problems,
    cluster_view,
    drainable,
    evaluate,
)
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.ops.numpy_binpack import binpack_numpy
from karpenter_tpu.runtime import KarpenterRuntime, Options
from karpenter_tpu.solver import SolverService
from karpenter_tpu.store import Store
from karpenter_tpu.utils.quantity import Quantity


def q(value):
    return Quantity.parse(str(value))


def make_node(name, cpu="8", memory="16Gi", pods="16", labels=None,
              ready=True, taints=(), annotations=None):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels=dict(labels or {"pool": "a"}),
            annotations=dict(annotations or {}),
        ),
        spec=NodeSpec(taints=list(taints)),
        status=NodeStatus(
            allocatable={
                "cpu": q(cpu), "memory": q(memory), "pods": q(pods)
            },
            conditions=[
                NodeCondition("Ready", "True" if ready else "False")
            ],
        ),
    )


def make_pod(name, node, cpu="1", memory="1Gi", node_selector=None,
             tolerations=(), annotations=None):
    return Pod(
        metadata=ObjectMeta(
            name=name, annotations=dict(annotations or {})
        ),
        spec=PodSpec(
            node_name=node,
            containers=[
                Container(requests={"cpu": q(cpu), "memory": q(memory)})
            ],
            node_selector=dict(node_selector or {}),
            tolerations=list(tolerations),
        ),
    )


def make_producer(name="pc", selector=None, ref="grp"):
    return MetricsProducer(
        metadata=ObjectMeta(name=name),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector=dict(selector or {"pool": "a"}),
                node_group_ref=ref,
            )
        ),
    )


def store_with(nodes=(), pods=(), producers=(), groups=()):
    store = Store()
    for obj in (*producers, *groups, *nodes, *pods):
        store.create(obj)
    return store


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def service():
    svc = SolverService(registry=GaugeRegistry(), window_s=0.02)
    yield svc
    svc.close()


class TestPlannerVerdicts:
    def test_empty_node_is_trivially_drainable(self, service):
        store = store_with(
            nodes=[make_node("n0"), make_node("n1")],
            producers=[make_producer()],
        )
        view = cluster_view(store)
        verdicts = evaluate(view, ["n0", "n1"], service, backend="xla")
        assert verdicts == {"n0": True, "n1": True}
        # nothing to re-pack: no solve was needed at all
        assert service.stats.requests == 0

    def test_pod_repacks_onto_free_node(self, service):
        store = store_with(
            nodes=[make_node("n0"), make_node("n1")],
            pods=[make_pod("p0", "n0")],
            producers=[make_producer()],
        )
        verdicts = evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        )
        assert verdicts == {"n0": True}

    def test_no_receiver_vetoes(self, service):
        store = store_with(
            nodes=[make_node("n0")],
            pods=[make_pod("p0", "n0")],
            producers=[make_producer()],
        )
        verdicts = evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        )
        assert verdicts == {"n0": False}

    def test_overfull_remainder_vetoes(self, service):
        # n1's free capacity (8 - 6 = 2 cpu) cannot absorb n0's 4-cpu pod
        store = store_with(
            nodes=[make_node("n0"), make_node("n1")],
            pods=[
                make_pod("p0", "n0", cpu="4"),
                make_pod("p1", "n1", cpu="6"),
            ],
            producers=[make_producer()],
        )
        verdicts = evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        )
        assert verdicts == {"n0": False}

    def test_node_selector_respected(self, service):
        # the only other node lacks the pod's required label
        store = store_with(
            nodes=[
                make_node("n0", labels={"pool": "a", "disk": "ssd"}),
                make_node("n1"),
            ],
            pods=[
                make_pod("p0", "n0", node_selector={"disk": "ssd"})
            ],
            producers=[make_producer()],
        )
        verdicts = evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        )
        assert verdicts == {"n0": False}

    def test_untolerated_taint_respected(self, service):
        taint = Taint(key="dedicated", value="x", effect="NoSchedule")
        store = store_with(
            nodes=[make_node("n0"), make_node("n1", taints=[taint])],
            pods=[make_pod("p0", "n0")],
            producers=[make_producer()],
        )
        assert evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        ) == {"n0": False}
        # a toleration flips the verdict
        store = store_with(
            nodes=[make_node("n0"), make_node("n1", taints=[taint])],
            pods=[
                make_pod(
                    "p0", "n0",
                    tolerations=[
                        Toleration(
                            key="dedicated", operator="Equal",
                            value="x", effect="NoSchedule",
                        )
                    ],
                )
            ],
            producers=[make_producer()],
        )
        assert evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        ) == {"n0": True}

    def test_cordoned_receiver_excluded(self, service):
        receiver = make_node("n1")
        receiver.spec.unschedulable = True
        store = store_with(
            nodes=[make_node("n0"), receiver],
            pods=[make_pod("p0", "n0")],
            producers=[make_producer()],
        )
        assert evaluate(
            cluster_view(store), ["n0"], service, backend="xla"
        ) == {"n0": False}

    def test_do_not_disrupt_marks_view(self):
        store = store_with(
            nodes=[make_node("n0"), make_node("n1")],
            pods=[
                make_pod(
                    "p0", "n0", annotations={DO_NOT_DISRUPT: "true"}
                )
            ],
            producers=[make_producer()],
        )
        by_name = cluster_view(store).by_name()
        assert by_name["n0"].do_not_disrupt
        assert not by_name["n1"].do_not_disrupt


def random_cluster(seed, nodes=8, pods=40):
    """A rng fragmented cluster: skewed pod placement, mixed sizes,
    some selector-constrained pods."""
    rng = np.random.default_rng(seed)
    node_objs = [
        make_node(
            f"n{i}",
            cpu=str(int(rng.choice([4, 8, 16]))),
            labels={
                "pool": "a",
                "zone": f"z{i % 2}",
            },
        )
        for i in range(nodes)
    ]
    pod_objs = []
    for i in range(pods):
        n = int(nodes * rng.random() ** 2) % nodes
        selector = (
            {"zone": f"z{int(rng.integers(0, 2))}"}
            if rng.random() < 0.3
            else None
        )
        pod_objs.append(
            make_pod(
                f"p{i}", f"n{n}",
                cpu=str(float(rng.choice([0.25, 0.5, 1.0, 2.0]))),
                memory=f"{int(rng.choice([256, 512, 1024]))}Mi",
                node_selector=selector,
            )
        )
    return store_with(
        nodes=node_objs, pods=pod_objs, producers=[make_producer()]
    )


class TestBatchedVerdictProperty:
    """Satellite acceptance: the batched consolidation verdict for N
    candidates is element-for-element identical to N independent masked
    bin-packs — device path and numpy fallback path both, and the two
    agree with each other bit-identically."""

    @pytest.mark.parametrize("seed", range(4))
    def test_batched_equals_independent_both_backends(self, seed):
        store = random_cluster(seed)
        view = cluster_view(store)
        names = [nv.name for nv in view.nodes]
        solved, inputs, trivial = build_problems(view, names)
        assert inputs, "cluster should produce at least one solve"

        svc = SolverService(registry=GaugeRegistry(), window_s=0.02)
        try:
            batched_xla = svc.consolidate(inputs, backend="xla")
            batched_np = svc.consolidate(inputs, backend="numpy")
        finally:
            svc.close()
        independent_xla = [B.solve(x, backend="xla") for x in inputs]
        independent_np = [binpack_numpy(x) for x in inputs]

        for name, bx, bn, ix, zn in zip(
            solved, batched_xla, batched_np, independent_xla,
            independent_np,
        ):
            for a, b in ((bx, ix), (bn, zn), (bx, bn)):
                for field in (
                    "assigned", "assigned_count", "nodes_needed",
                ):
                    np.testing.assert_array_equal(
                        np.asarray(getattr(a, field)),
                        np.asarray(getattr(b, field)),
                        err_msg=f"{name}:{field}",
                    )
                assert int(a.unschedulable) == int(b.unschedulable)
                assert drainable(a) == drainable(b), name


class TestServiceConsolidateSeam:
    def test_empty_batch(self, service):
        assert service.consolidate([]) == []

    def test_one_dispatch_per_batch(self, service):
        store = random_cluster(1)
        view = cluster_view(store)
        _, inputs, _ = build_problems(
            view, [nv.name for nv in view.nodes]
        )
        assert len(inputs) >= 4
        before = service.stats.dispatches
        service.consolidate(inputs, backend="xla")
        assert service.stats.dispatches == before + 1

    def test_zero_recompiles_across_candidate_jitter(self, service):
        """Candidate counts wandering inside one batch rung (and pod
        counts inside one pod rung) hit the same compiled program."""
        store = random_cluster(2, nodes=10, pods=50)
        view = cluster_view(store)
        _, inputs, _ = build_problems(
            view, [nv.name for nv in view.nodes]
        )
        assert len(inputs) >= 6
        service.consolidate(inputs[:6], backend="xla")  # warm rung 6
        misses = service.stats.compile_cache_misses
        service.consolidate(inputs[:5], backend="xla")
        service.consolidate(inputs[:6], backend="xla")
        assert service.stats.compile_cache_misses == misses
        assert service.stats.compile_cache_hits >= 2

    def test_batch_larger_than_max_batch_one_dispatch(self):
        """consolidate() batches are atomic: the worker drains past
        max_batch so the whole candidate set rides one dispatch."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.02, max_batch=2
        )
        try:
            store = random_cluster(3, nodes=10, pods=50)
            view = cluster_view(store)
            _, inputs, _ = build_problems(
                view, [nv.name for nv in view.nodes]
            )
            assert len(inputs) > 2
            before = svc.stats.dispatches
            svc.consolidate(inputs, backend="xla")
            assert svc.stats.dispatches == before + 1
        finally:
            svc.close()


def consolidating_runtime(replicas=3, budget=1):
    clock = FakeClock()
    provider = FakeFactory()
    provider.node_replicas["grp-id"] = replicas
    runtime = KarpenterRuntime(
        Options(consolidate=True),
        cloud_provider_factory=provider,
        clock=clock,
    )
    runtime.consolidation.config.budget_per_group = budget
    runtime.store.create(make_producer())
    runtime.store.create(
        ScalableNodeGroup(
            metadata=ObjectMeta(name="grp"),
            spec=ScalableNodeGroupSpec(
                replicas=replicas, type=FAKE_NODE_GROUP, id="grp-id"
            ),
        )
    )
    return runtime, provider, clock


class TestEngineStateMachine:
    def test_cooldown_then_cordon_verify_drain(self):
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            for i in range(3):
                store.create(make_node(f"n{i}"))
            store.create(make_pod("p0", "n0"))

            # first sight starts the churn clock: nothing is touched
            assert engine.plan() == {}
            assert engine.in_flight() == {}

            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            # budget 1: exactly one node cordoned (an empty one first)
            assert list(engine.in_flight().values()) == ["cordoned"]
            cordoned = next(iter(engine.in_flight()))
            node = store.get("Node", "default", cordoned)
            assert node.spec.unschedulable
            assert (
                node.metadata.annotations[
                    "karpenter.sh/consolidation-state"
                ]
                == "cordoned"
            )

            # verify soak: still cordoned before verify_s elapses
            clock.advance(1)
            engine.plan()
            assert engine.in_flight()[cordoned] == "cordoned"

            clock.advance(engine.config.verify_s)
            engine.plan()
            assert engine.in_flight()[cordoned] == "draining"
            sng = store.get("ScalableNodeGroup", "default", "grp")
            assert sng.spec.replicas == 2  # intent decremented

            # the controller actuates the shrink and finalizes the drain
            runtime.manager.converge(2)
            assert engine.in_flight() == {}
            assert provider.node_replicas["grp-id"] == 2
            names = {
                n.metadata.name for n in store.list("Node")
            }
            assert cordoned not in names
        finally:
            runtime.close()

    def test_verdict_flip_uncordons_and_counts_veto(self):
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            store.create(make_node("n0"))
            store.create(make_node("n1"))
            store.create(make_pod("p0", "n0"))
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            # emptiest-first: n1 (no pods) cordons
            assert engine.in_flight() == {"n1": "cordoned"}

            # cluster changes under the soak: n1 receives nothing, but
            # n0's drain target vanishes — delete the OTHER node so the
            # re-verify of n1 sees... n1 is empty, still drainable.
            # Flip it instead by filling n1 with a pod (bypassing the
            # cordon): now n1 has a pod and n0 is the only receiver —
            # give n0 no headroom first.
            store.create(make_pod("big0", "n0", cpu="7"))
            store.create(make_pod("p1", "n1", cpu="4"))
            clock.advance(engine.config.verify_s + 1)
            engine.plan()
            assert engine.in_flight() == {}
            node = store.get("Node", "default", "n1")
            assert not node.spec.unschedulable
            assert (
                engine.registry.gauge(
                    "consolidation", "drains_vetoed_total"
                ).get("-", "-")
                == 1.0
            )
        finally:
            runtime.close()

    def test_drain_timeout_vetoes_and_frees_budget(self):
        """A DRAINING node whose scale-down never lands (a concurrent
        spec writer keeps reverting the decrement) is returned to
        service after drain_timeout_s instead of holding the cordon and
        the budget slot forever."""
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            store.create(make_node("n0"))
            store.create(make_node("n1"))
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            clock.advance(engine.config.verify_s + 1)
            engine.plan()
            (draining,) = [
                n for n, p in engine.in_flight().items()
                if p == "draining"
            ]
            # an HPA-like writer reverts the consolidation decrement,
            # so the controller never observes spec < observed
            from karpenter_tpu.store.store import Scale

            store.update_scale(
                "ScalableNodeGroup",
                Scale("default", "grp", 3, 3),
            )
            clock.advance(engine.config.drain_timeout_s + 1)
            engine.plan()
            assert draining not in engine.in_flight()
            node = store.get("Node", "default", draining)
            assert not node.spec.unschedulable
            assert (
                engine.registry.gauge(
                    "consolidation", "drains_vetoed_total"
                ).get("-", "-")
                == 1.0
            )
        finally:
            runtime.close()

    def test_failed_uncordon_retries_until_it_lands(self):
        """A veto whose uncordon write fails must keep owning the node
        (UNCORDONING phase) and retry, never strand it unschedulable."""
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            store.create(make_node("n0"))
            store.create(make_node("n1"))
            store.create(make_pod("p0", "n0"))
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            assert engine.in_flight() == {"n1": "cordoned"}

            # flip the verdict (fill the only receiver) and make the
            # uncordon write fail transiently
            store.create(make_pod("big", "n0", cpu="7"))
            store.create(make_pod("p1", "n1", cpu="4"))
            real_update = store.update

            def failing_update(obj):
                raise RuntimeError("injected conflict")

            store.update = failing_update
            clock.advance(engine.config.verify_s + 1)
            engine.plan()
            assert engine.in_flight() == {"n1": "uncordoning"}
            assert store.get("Node", "default", "n1").spec.unschedulable

            store.update = real_update
            clock.advance(1)
            engine.plan()
            assert engine.in_flight() == {}
            assert not store.get(
                "Node", "default", "n1"
            ).spec.unschedulable
        finally:
            runtime.close()

    def test_do_not_disrupt_blocks_candidacy(self):
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            store.create(
                make_node(
                    "n0", annotations={DO_NOT_DISRUPT: "true"}
                )
            )
            store.create(make_node("n1"))
            store.create(
                make_pod(
                    "p0", "n1", annotations={DO_NOT_DISRUPT: "true"}
                )
            )
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            verdicts = engine.plan()
            assert verdicts == {}  # neither node is even evaluated
            assert engine.in_flight() == {}
        finally:
            runtime.close()

    def test_budget_bounds_concurrent_disruption(self):
        runtime, provider, clock = consolidating_runtime(budget=2)
        try:
            engine = runtime.consolidation
            store = runtime.store
            for i in range(5):
                store.create(make_node(f"n{i}"))
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            assert (
                sorted(engine.in_flight().values())
                == ["cordoned", "cordoned"]
            )
        finally:
            runtime.close()

    def test_pod_churn_resets_cooldown(self):
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            store.create(make_node("n0"))
            store.create(make_node("n1"))
            engine.plan()
            clock.advance(engine.config.cooldown_s - 5)
            # a pod lands on n0 just before its cooldown expires
            store.create(make_pod("late", "n0"))
            engine.plan()
            clock.advance(10)
            engine.plan()
            # n1 aged out and cordoned; n0's clock restarted
            flight = engine.in_flight()
            assert "n0" not in flight and "n1" in flight
        finally:
            runtime.close()

    def test_nodes_without_group_ref_never_actuate(self):
        runtime, provider, clock = consolidating_runtime()
        try:
            engine = runtime.consolidation
            store = runtime.store
            # a node outside every producer selector
            store.create(make_node("n0", labels={"pool": "other"}))
            store.create(make_node("n1"))
            engine.plan()
            clock.advance(engine.config.cooldown_s + 1)
            engine.plan()
            assert "n0" not in engine.in_flight()
        finally:
            runtime.close()


class TestScaleDownCondition:
    """Satellite: a scale-down actuating while the group is unstable is
    surfaced as a structured condition (reason + transition timestamp)
    on the API object, not just a log line."""

    def _reconcile(self, stable):
        from karpenter_tpu.controllers.scalablenodegroup import (
            ScalableNodeGroupController,
        )

        provider = FakeFactory()
        provider.node_replicas["grp-id"] = 3
        provider.node_group_stable = stable
        controller = ScalableNodeGroupController(provider)
        resource = ScalableNodeGroup(
            metadata=ObjectMeta(name="grp"),
            spec=ScalableNodeGroupSpec(
                replicas=1, type=FAKE_NODE_GROUP, id="grp-id"
            ),
        )
        controller.reconcile(resource)
        return provider, resource

    def test_unstable_scale_down_emits_structured_condition(self):
        provider, resource = self._reconcile(stable=False)
        assert provider.node_replicas["grp-id"] == 1  # still actuated
        condition = resource.status_conditions().get("Stabilized")
        assert condition.status == "False"
        assert condition.reason == "ScaleDownWhileUnstable"
        assert "3->1" in condition.message
        assert condition.last_transition_time > 0

    def test_stable_scale_down_leaves_condition_clean(self):
        provider, resource = self._reconcile(stable=True)
        assert provider.node_replicas["grp-id"] == 1
        condition = resource.status_conditions().get("Stabilized")
        assert condition.status == "True"
        assert condition.reason == ""


class TestSimulateConsolidation:
    def test_dry_run_report_and_no_mutation(self, service):
        from karpenter_tpu.simulate import simulate_consolidation

        store = store_with(
            nodes=[
                make_node("n0"),
                make_node("n1"),
                make_node("n2", labels={"pool": "other"}),
            ],
            pods=[
                make_pod("p0", "n0", cpu="7"),
                make_pod("p1", "n0", cpu="2"),
            ],
            producers=[make_producer()],
        )
        report = simulate_consolidation(store, service=service)
        assert report["nodes"]["n1"]["drainable"] is True
        assert report["nodes"]["n0"]["drainable"] is False  # too big
        assert (
            report["nodes"]["n2"]["ineligible"]
            == "no nodeGroupRef to actuate"
        )
        assert report["drainable"] == ["n1"]
        assert report["candidates_evaluated"] == 2
        # dry run: nothing cordoned, nothing scaled, nothing deleted
        assert all(
            not n.spec.unschedulable for n in store.list("Node")
        )

    def test_runtime_wires_engine_only_when_opted_in(self):
        runtime = KarpenterRuntime(
            cloud_provider_factory=FakeFactory()
        )
        try:
            assert runtime.consolidation is None
        finally:
            runtime.close()
