"""Crash-safe controller state: unit + property layer.

Pins the ISSUE 7 satellite contracts for karpenter_tpu/recovery:

  * journal replay is IDEMPOTENT (replaying the same journal twice from
    the same checkpoint yields identical state) and checkpoint + journal
    tail == journal-only, at every split point — property-pinned over
    seeded random op streams;
  * a torn final record (crash mid-append) is discarded and the file
    repaired to a record boundary;
  * the actuation fence: monotonic generations across incarnations on
    one journal dir, provider-side rejection of superseded stamps;
  * DecorrelatedJitterBackoff state restores from the journal with
    restored due-times capped at now + cap (a long-dead object is never
    stuck parked);
  * circuit-breaker state restores (a provider flapping before the
    crash is still circuit-broken after it);
  * the recovery-boot cache invalidation seams: SolverService
    .reset_caches() and SnapshotDeltaCache.reset();
  * warm-up semantics: a RECOVERED boot holds disruption until the
    configured ticks complete; first boots skip the warm-up.

`make test-recovery` runs this file + tests/test_restart_chaos.py.
"""

import os
import random

import pytest

from karpenter_tpu import faults
from karpenter_tpu.faults import FaultRegistry, ProcessCrash
from karpenter_tpu.recovery import (
    ActuationFence,
    FenceRejectedError,
    FenceToken,
    FenceValidator,
    RecoveryManager,
    StateJournal,
    key_str,
    key_tuple,
    replay,
)
from karpenter_tpu.recovery.journal import apply_record


@pytest.fixture(autouse=True)
def _no_leaked_registry():
    yield
    faults.uninstall()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# journal basics
# ---------------------------------------------------------------------------


class TestJournal:
    def test_append_recover_roundtrip(self, tmp_path):
        j = StateJournal(str(tmp_path))
        h = j.handle("demo")
        h.set(("node", "n1"), {"phase": "cordoned"})
        h.set(("node", "n2"), {"phase": "draining"})
        h.delete(("node", "n1"))
        h.append_sample(("ring", "a"), 1.0, 2.0, cap=3)
        j.close()

        j2 = StateJournal(str(tmp_path))
        checkpoint, records = j2.recover()
        state = replay(checkpoint, records)
        assert state["demo"] == {
            key_str(("node", "n2")): {"phase": "draining"},
            key_str(("ring", "a")): [[1.0, 2.0]],
        }
        j2.close()

    def test_ring_appends_bounded_by_cap(self, tmp_path):
        j = StateJournal(str(tmp_path))
        h = j.handle("history")
        for i in range(10):
            h.append_sample(("s",), float(i), float(i), cap=4)
        checkpoint, records = j.recover()
        state = replay(checkpoint, records)
        ring = state["history"][key_str(("s",))]
        assert ring == [[float(i), float(i)] for i in range(6, 10)]
        j.close()

    def test_torn_final_record_discarded_and_repaired(self, tmp_path):
        j = StateJournal(str(tmp_path))
        h = j.handle("demo")
        h.set(("a",), 1)
        h.set(("b",), 2)
        j.close()
        path = os.path.join(str(tmp_path), "state-journal.jsonl")
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"sub": "demo", "op": "set", "k"')  # torn tail

        j2 = StateJournal(str(tmp_path))
        checkpoint, records = j2.recover()
        assert len(records) == 2
        state = replay(checkpoint, records)
        assert state["demo"] == {key_str(("a",)): 1, key_str(("b",)): 2}
        # the fragment is gone: appends resume on a record boundary
        j2.handle("demo").set(("c",), 3)
        j2.close()
        j3 = StateJournal(str(tmp_path))
        _, records = j3.recover()
        assert replay(None, records)["demo"][key_str(("c",))] == 3
        j3.close()

    def test_compaction_bounds_journal(self, tmp_path):
        j = StateJournal(
            str(tmp_path), compact_every=8, compact_min_interval_s=0.0
        )
        table = {}

        def provider():
            return {"demo": dict(table)}

        j.checkpoint_provider = provider
        h = j.handle("demo")
        for i in range(50):
            table[key_str(("k", i % 4))] = i
            h.set(("k", i % 4), i)
        # the journal truncated at least once: far fewer live records
        # than appends, and recovery still yields the full table
        checkpoint, records = j.recover()
        assert len(records) < 8
        assert replay(checkpoint, records)["demo"] == table
        j.close()

    def test_append_never_raises_after_close(self, tmp_path):
        j = StateJournal(str(tmp_path))
        j.close()
        j.handle("demo").set(("a",), 1)  # crashed incarnation: no-op

    def test_crash_fault_leaves_recoverable_torn_record(self, tmp_path):
        """The process.crash injection point inside append flushes a
        REAL half-record before dying; recovery discards it and keeps
        everything before."""
        j = StateJournal(str(tmp_path))
        h = j.handle("demo")
        h.set(("a",), {"value": 1})
        with FaultRegistry(seed=1) as reg:
            reg.plan("process.crash.journal", mode="crash", times=1)
            with pytest.raises(ProcessCrash):
                h.set(("b",), {"value": 2})
        j.close()
        j2 = StateJournal(str(tmp_path))
        checkpoint, records = j2.recover()
        state = replay(checkpoint, records)
        assert state["demo"] == {key_str(("a",)): {"value": 1}}
        j2.close()

    def test_key_roundtrip_nested(self):
        for key in [
            ("node", "n1"),
            ("q", "metric", (("a", "1"), ("b", "2"))),
            ("charge", "ns", "grp"),
            ("ha", "default", "ha", 0),
        ]:
            assert key_tuple(key_str(key)) == key


# ---------------------------------------------------------------------------
# replay properties (satellite: property-pin replay idempotency and
# checkpoint+journal == journal-only equivalence)
# ---------------------------------------------------------------------------


def _random_records(rng, n):
    records = []
    for _ in range(n):
        sub = rng.choice(("consolidation", "preemption", "history"))
        k = key_str((rng.choice("abcd"), rng.randrange(3)))
        op = rng.choice(("set", "set", "del", "append"))
        if op == "set":
            records.append(
                {"sub": sub, "op": "set", "k": k,
                 "v": {"x": rng.randrange(100)}}
            )
        elif op == "del":
            records.append({"sub": sub, "op": "del", "k": k})
        else:
            records.append(
                {"sub": sub, "op": "append", "k": k,
                 "t": rng.random(), "v": rng.random(), "cap": 4}
            )
    return records


class TestReplayProperties:
    @pytest.mark.parametrize("seed", range(8))
    def test_replay_is_idempotent(self, seed):
        records = _random_records(random.Random(seed), 200)
        assert replay(None, records) == replay(None, records)

    @pytest.mark.parametrize("seed", range(8))
    def test_checkpoint_plus_tail_equals_full_journal(self, seed):
        rng = random.Random(seed)
        records = _random_records(rng, 200)
        full = replay(None, records)
        for split in sorted(rng.sample(range(201), 8)):
            checkpoint = {"state": replay(None, records[:split])}
            assert replay(checkpoint, records[split:]) == full

    @pytest.mark.parametrize("seed", range(4))
    def test_on_disk_roundtrip_matches_in_memory_fold(self, seed, tmp_path):
        """Writing the stream through a real journal (with compaction
        forcing checkpoints mid-stream) recovers to the same state as
        the pure in-memory fold."""
        records = _random_records(random.Random(seed), 120)
        expected = replay(None, records)

        state = {}
        j = StateJournal(
            str(tmp_path), compact_every=16, compact_min_interval_s=0.0
        )
        j.checkpoint_provider = lambda: {
            sub: dict(t) for sub, t in state.items()
        }
        for record in records:
            apply_record(state, record)
            j.record(record)
        j.close()

        j2 = StateJournal(str(tmp_path))
        checkpoint, tail = j2.recover()
        assert replay(checkpoint, tail) == expected
        j2.close()


# ---------------------------------------------------------------------------
# fence
# ---------------------------------------------------------------------------


class TestFence:
    def test_generation_monotonic_across_incarnations(self, tmp_path):
        gens = [ActuationFence(str(tmp_path)).generation for _ in range(3)]
        assert gens == [1, 2, 3]

    def test_validator_rejects_superseded_generation(self):
        validator = FenceValidator()
        validator.admit(FenceToken(generation=1))
        validator.admit(FenceToken(generation=2))
        with pytest.raises(FenceRejectedError) as err:
            validator.admit(FenceToken(generation=1))
        assert err.value.code == "FenceRejected"
        assert err.value.retryable  # soft failure for the zombie
        assert validator.rejections == 1
        # the live generation is never blocked
        validator.admit(FenceToken(generation=2))

    def test_unstamped_calls_pass(self):
        validator = FenceValidator()
        validator.admit(None)
        validator.admit(FenceToken(generation=5))
        validator.admit(None)  # unfenced legacy caller still fine
        assert validator.rejections == 0

    def test_fence_file_survives_torn_write(self, tmp_path):
        ActuationFence(str(tmp_path))  # gen 1
        # a torn tmp file from a crashed claim must not poison the next
        tmp = os.path.join(str(tmp_path), "FENCE.tmp")
        with open(tmp, "w") as f:
            f.write("garb")
        assert ActuationFence(str(tmp_path)).generation == 2


# ---------------------------------------------------------------------------
# manager: warm-up + tables + checkpoint merge
# ---------------------------------------------------------------------------


class TestRecoveryManager:
    def test_first_boot_skips_warmup(self, tmp_path):
        mgr = RecoveryManager(str(tmp_path), warmup_ticks=3)
        assert not mgr.recovered
        assert mgr.allow_disruption()
        mgr.close()

    def test_recovered_boot_holds_warmup_for_ticks(self, tmp_path):
        mgr = RecoveryManager(str(tmp_path), warmup_ticks=2)
        mgr.handle("demo").set(("a",), 1)
        mgr.close()
        mgr2 = RecoveryManager(str(tmp_path), warmup_ticks=2)
        assert mgr2.recovered
        assert not mgr2.allow_disruption()
        mgr2.on_tick()
        assert not mgr2.allow_disruption()
        mgr2.on_tick()
        assert mgr2.allow_disruption()
        assert mgr2.table("demo") == {key_str(("a",)): 1}
        mgr2.close()

    def test_boot_compacts_and_unregistered_tables_survive(self, tmp_path):
        """finish_boot() checkpoints the replayed state; a subsystem NOT
        running this incarnation (feature toggled off) keeps its table
        verbatim through the checkpoint instead of losing it."""
        mgr = RecoveryManager(str(tmp_path))
        mgr.handle("consolidation").set(("node", "n1"), {"phase": "cordoned"})
        mgr.close()

        mgr2 = RecoveryManager(str(tmp_path))
        mgr2.register_snapshot("other", lambda: {key_str(("x",)): 7})
        mgr2.finish_boot()  # compacts: checkpoint written, journal empty
        mgr2.close()

        mgr3 = RecoveryManager(str(tmp_path))
        assert mgr3.table("consolidation") == {
            key_str(("node", "n1")): {"phase": "cordoned"}
        }
        assert mgr3.table("other") == {key_str(("x",)): 7}
        mgr3.close()


# ---------------------------------------------------------------------------
# restored subsystem state: backoff cap, breakers, cache resets
# ---------------------------------------------------------------------------


def _runtime(tmp_path, clock, provider, store=None, **opts):
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    return KarpenterRuntime(
        Options(journal_dir=str(tmp_path), **opts),
        store=store,
        cloud_provider_factory=provider,
        clock=clock,
    )


def _kill(runtime):
    """Abandon an incarnation the way SIGKILL would: no graceful
    checkpoint, just stop its threads and drop its journal handle."""
    runtime.solver_service.close()
    runtime.recovery.journal.close()


class TestBackoffRestore:
    def test_backoff_restored_and_due_capped(self, tmp_path):
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.store import Store

        store = Store()
        clock = FakeClock()
        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        rt1 = _runtime(
            tmp_path, clock, provider, store=store,
            backoff_base_s=1.0, backoff_cap_s=60.0,
        )
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        # a flaky store: every status patch fails, so each reconcile
        # requeues on the backoff ladder (the rung that journals)
        registry = faults.install(FaultRegistry(seed=3))
        registry.plan("store.patch_status", probability=1.0)
        for _ in range(6):
            clock.advance(120.0)
            rt1.manager._due = {k: 0.0 for k in rt1.manager._due}
            rt1.manager.reconcile_all()
        faults.uninstall()
        key = ("ScalableNodeGroup", "default", "g")
        prev1 = rt1.manager._backoff_prev[key]
        assert prev1 > 1.0
        _kill(rt1)

        # long outage between crash and restart: the journaled due time
        # is far in the past / the prev delay large — the restored due
        # must be capped at now + cap, never parking the object
        clock.advance(10_000.0)
        rt2 = _runtime(
            tmp_path, clock, provider, store=store,
            backoff_base_s=1.0, backoff_cap_s=60.0,
        )
        try:
            assert rt2.manager._backoff_prev[key] == pytest.approx(prev1)
            assert rt2.manager._due[key] <= clock() + 60.0
            assert rt2.manager._due[key] != float("inf")
        finally:
            rt2.close()

    def test_restore_prunes_objects_deleted_during_downtime(
        self, tmp_path
    ):
        """An object whose backoff was journaled, then deleted while
        the controller was down: the restore must boot cleanly (the
        prune deletes fold into the very table being restored — a live
        mirror) and drop the entry instead of reviving it."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.store import Store

        store = Store()
        clock = FakeClock()
        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        provider.node_replicas["h"] = 1
        rt1 = _runtime(tmp_path, clock, provider, store=store)
        for name in ("g", "h"):
            store.create(
                ScalableNodeGroup(
                    metadata=ObjectMeta(name=name),
                    spec=ScalableNodeGroupSpec(
                        replicas=1, type="FakeNodeGroup", id=name
                    ),
                )
            )
        registry = faults.install(FaultRegistry(seed=5))
        registry.plan("store.patch_status", probability=1.0)
        for _ in range(3):
            clock.advance(120.0)
            rt1.manager._due = {k: 0.0 for k in rt1.manager._due}
            rt1.manager.reconcile_all()
        faults.uninstall()
        assert len(rt1.manager._backoff_prev) == 2
        _kill(rt1)

        store.delete("ScalableNodeGroup", "default", "g")  # while down
        rt2 = _runtime(tmp_path, clock, provider, store=store)
        try:
            key_g = ("ScalableNodeGroup", "default", "g")
            key_h = ("ScalableNodeGroup", "default", "h")
            assert key_g not in rt2.manager._backoff_prev
            assert key_h in rt2.manager._backoff_prev
        finally:
            rt2.close()


class TestBreakerRestore:
    def test_open_breaker_survives_restart(self, tmp_path):
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import (
            FakeFactory,
            retryable_error,
        )
        from karpenter_tpu.store import Store

        store = Store()
        clock = FakeClock()
        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        provider.want_err = retryable_error("Throttling")
        rt1 = _runtime(
            tmp_path, clock, provider, store=store,
            circuit_failure_threshold=2, circuit_reset_s=300.0,
        )
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        for _ in range(3):
            clock.advance(120.0)
            rt1.manager._due = {k: 0.0 for k in rt1.manager._due}
            rt1.manager.reconcile_all()
        sng_ctrl = rt1.manager._controllers[1]
        assert sng_ctrl._breakers[("default", "g")].state == "open"
        _kill(rt1)

        provider.want_err = None  # the provider healed while we were dead
        clock.advance(1.0)
        rt2 = _runtime(
            tmp_path, clock, provider, store=store,
            circuit_failure_threshold=2, circuit_reset_s=300.0,
        )
        try:
            ctrl2 = rt2.manager._controllers[1]
            breaker = ctrl2._breakers[("default", "g")]
            # still OPEN: a provider that was flapping before the crash
            # does not get a clean slate by crashing us
            assert breaker.state == "open"
            assert breaker.consecutive_failures >= 2
            # ...and the normal half-open probe heals it
            clock.advance(301.0)
            rt2.manager._due = {k: 0.0 for k in rt2.manager._due}
            rt2.manager.reconcile_all()
            assert breaker.state == "closed"
        finally:
            rt2.close()


class TestCacheResetSeams:
    def test_solver_service_reset_caches(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.solver import SolverService

        svc = SolverService(registry=GaugeRegistry())
        try:
            svc._compiled[("fake-key",)] = lambda: None
            svc._compile_seen.add(("fake-key",))
            svc.reset_caches()
            assert svc._compiled == {}
            assert svc._compile_seen == set()
        finally:
            svc.close()

    def test_delta_cache_reset(self):
        from karpenter_tpu.metrics.producers.pendingcapacity.encoder import (
            SnapshotDeltaCache,
        )

        cache = SnapshotDeltaCache()
        cache._entries["k"] = object()
        cache.reset()
        assert len(cache._entries) == 0

    def test_recovery_boot_invalidates_process_caches(self, tmp_path):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encoder,
        )

        clock = FakeClock()
        rt1 = _runtime(tmp_path, clock, FakeFactory())
        _kill(rt1)  # leaves journal state + fence generation behind

        from karpenter_tpu.solver.service import (
            default_service,
            reset_default_service,
        )

        encoder._default_delta._entries["stale"] = object()
        shared = default_service()  # outlives in-process restarts
        shared._compiled[("stale",)] = lambda: None
        shared._compile_seen.add(("stale",))
        rt2 = _runtime(tmp_path, clock, FakeFactory())
        try:
            assert rt2.recovery.recovered
            # the recovery boot reset the process-level caches:
            # pre-crash identity-keyed entries must not be reused
            assert len(encoder._default_delta._entries) == 0
            assert shared._compiled == {}
            assert shared._compile_seen == set()
            assert rt2.solver_service._compiled == {}
        finally:
            rt2.close()
            reset_default_service()


class TestJournalGauges:
    def test_gauges_registered_and_updated(self, tmp_path):
        from karpenter_tpu.metrics.registry import GaugeRegistry

        registry = GaugeRegistry()
        mgr = RecoveryManager(str(tmp_path), registry=registry)
        mgr.handle("demo").set(("a",), 1)
        mgr.on_tick()
        assert (
            registry.gauge("recovery", "replay_seconds").get("-", "-")
            is not None
        )
        assert (
            registry.gauge("recovery", "journal_bytes").get("-", "-") > 0
        )
        assert (
            registry.gauge(
                "recovery", "warmup_ticks_remaining"
            ).get("-", "-")
            == 0.0
        )
        mgr.close()


class TestZombieSelfFence:
    def test_stale_incarnation_cannot_overwrite_live_state(self, tmp_path):
        """Rolling-restart overlap: the OLD incarnation is still alive
        when a NEW one claims the journal dir. The zombie's appends and
        its close-time checkpoint must be suppressed — otherwise its
        stale protective state would override the live incarnation's."""
        mgr1 = RecoveryManager(str(tmp_path))
        mgr1.handle("demo").set(("a",), "from-gen-1")

        mgr2 = RecoveryManager(str(tmp_path))  # supersedes gen 1
        mgr2.handle("demo").set(("a",), "from-gen-2")

        # the zombie keeps writing and then exits "gracefully" —
        # neither its append nor its checkpoint may land
        mgr1.handle("demo").set(("a",), "stale-zombie-write")
        mgr1.close()
        assert mgr1.journal._superseded

        mgr2.close()  # live incarnation checkpoints normally

        mgr3 = RecoveryManager(str(tmp_path))
        assert mgr3.table("demo") == {key_str(("a",)): "from-gen-2"}
        mgr3.close()

    def test_concurrent_claims_get_distinct_generations(self, tmp_path):
        """The fence claim is serialized under an exclusive flock: N
        racing boots must claim N distinct, strictly increasing
        generations (equal generations would both pass admit())."""
        import threading

        gens = []
        lock = threading.Lock()

        def claim():
            fence = ActuationFence(str(tmp_path))
            with lock:
                gens.append(fence.generation)

        threads = [threading.Thread(target=claim) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(gens) == list(range(1, 9))
