"""Event-driven reconcile engine tests (controllers/engine.py module
docstring "EVENT-DRIVEN RECONCILE").

The failure ladder (engine.py:18-33) predates event passes; this file
pins that the ladder's contracts hold THROUGH the event-pass path too:

  * a watch event on a DEACTIVATED key (due=inf) revives it through the
    event pass, not just the tick;
  * a non-retryable error raised INSIDE an event pass still deactivates
    (and a retryable one still rides the jittered backoff ladder);
  * a key the tick just reconciled is never double-reconciled by a
    racing event pass (dueness re-checked under the pass lock);
  * the resync backstop: with event PASSES suppressed entirely, the
    tick alone still converges, still runs the tick-hook consumers, and
    still picks up watch-revived keys;
  * wire compat: event_driven=False builds none of the machinery and
    marks nothing dirty.
"""

from __future__ import annotations

import pytest

import karpenter_tpu.cloudprovider.fake  # noqa: F401 — registers the FakeNodeGroup SNG type validator
from karpenter_tpu.controllers import Manager
from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import default_tracer
from karpenter_tpu.store import Store

from test_faults import FakeClock, CountingController, _sng

KEY = ("ScalableNodeGroup", "default", "g")
_NEVER = float("inf")


def make_manager(
    error_factory=None, event_driven=True, registry=None, tick_hook=None
):
    """Manager in manual event-pass mode (event_thread=False): tests
    drive run_event_pass on the fake clock, wall-free."""
    clock = FakeClock()
    store = Store()
    controller = CountingController(error_factory)
    manager = Manager(
        store, clock=clock, registry=registry, tick_hook=tick_hook,
        backoff_base_s=1.0, backoff_cap_s=30.0,
        event_driven=event_driven, event_debounce_s=0.05,
        event_thread=False,
    ).register(controller)
    store.create(_sng())
    return manager, controller, store, clock


def revive_patch(store):
    """An EXTERNAL spec edit — the documented revival signal (a watch
    event on the object itself, unlike the engine's own status echo)."""
    sng = store.get(*KEY)
    sng.spec.replicas = (sng.spec.replicas or 0) + 1
    store.update(sng)


class TestEventPassLadder:
    def test_deactivated_key_revives_through_event_pass(self):
        """engine.py ladder: due=inf is only exited by a watch event.
        With event passes, the revival must flow through the PASS —
        no tick involved."""
        manager, controller, store, clock = make_manager(
            lambda: ValueError("poisoned spec")  # non-retryable
        )
        clock.advance(10_000)
        manager.reconcile_all()
        assert controller.calls == 1
        assert manager._due[KEY] == _NEVER, "non-retryable deactivates"

        controller.error_factory = None  # the spec edit fixes it
        revive_patch(store)
        assert manager._due[KEY] == 0.0, "watch event revives due=inf"
        assert manager.dirty_count() == 1
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        assert controller.calls == 2, "revived THROUGH the event pass"
        assert manager._due[KEY] == pytest.approx(clock.now + 60.0)

    def test_non_retryable_error_in_event_pass_deactivates(self):
        """A poisoned object hit by an event pass must deactivate
        exactly as a tick would have — the pass is the same supervised
        workflow, not a shortcut around the ladder."""
        registry = GaugeRegistry()
        manager, controller, store, clock = make_manager(
            lambda: ValueError("poisoned"), registry=registry
        )
        revive_patch(store)
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        assert controller.calls == 1
        assert manager._due[KEY] == _NEVER
        assert registry.gauge(
            "resilience", "deactivated_total"
        ).get("ScalableNodeGroup", "-") == 1.0
        # deactivated: further passes have nothing due for it
        revive_patch(store)  # revives again (external edit)...
        controller.error_factory = None
        clock.advance(0.05)
        assert manager.run_event_pass() == 1  # ...and heals

    def test_retryable_error_in_event_pass_rides_backoff(self):
        """A retryable failure inside a pass lands on the jittered
        ladder; the key is NOT re-dispatched by further passes until
        the backoff expires (dirty keys respect the requeue ladder)."""
        manager, controller, store, clock = make_manager(
            lambda: RetryableError("throttled")
        )
        revive_patch(store)
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        delay = manager._due[KEY] - clock.now
        assert 0 < delay <= 30.0, "requeued on the backoff ladder"
        # the engine's own status patch must not have scheduled another
        # dispatchable pass for the key (it is not due)
        assert manager.run_event_pass() == 0
        assert controller.calls == 1

    def test_tick_and_event_pass_never_double_reconcile(self):
        """The race the pass lock + dueness re-check close: an event
        lands, the TICK gets there first, the debounced pass must then
        skip the key (it was requeued at now+interval)."""
        manager, controller, store, clock = make_manager(None)
        revive_patch(store)
        assert manager.dirty_count() == 1
        clock.advance(10_000)
        manager.reconcile_all()  # the tick wins the race
        assert controller.calls == 1
        assert manager.run_event_pass() == 0, (
            "the pass must re-check dueness and skip the key the tick "
            "just reconciled"
        )
        assert controller.calls == 1

    def test_event_racing_a_reconcile_is_not_swallowed(self):
        """A watch event landing WHILE the pass is reconciling the same
        key acted on state the reconcile never saw. The interval
        requeue must not overwrite the event's due-now stamp — the key
        stays due + dirty and the next pass re-reconciles, instead of
        parking until the backstop tick (the sequence re-check in
        _requeue)."""
        manager, controller, store, clock = make_manager(None)

        raced = {"done": False}
        original = controller.reconcile

        def reconcile_with_racing_event(obj):
            original(obj)
            if not raced["done"]:
                raced["done"] = True
                revive_patch(store)  # lands mid-reconcile

        controller.reconcile = reconcile_with_racing_event
        revive_patch(store)
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        assert manager._due[KEY] == 0.0, (
            "the raced event's due-now stamp must survive the requeue"
        )
        assert manager.dirty_count() == 1
        clock.advance(0.05)
        assert manager.run_event_pass() == 1, "the next pass re-reconciles"
        assert controller.calls == 2
        assert manager._due[KEY] == pytest.approx(clock.now + 60.0), (
            "no further event: the normal interval requeue resumes"
        )

    def test_deleted_dirty_key_is_not_counted_due(self):
        """A key deleted after dirtying (the Deleted handler pops its
        due entry) must not default to due-now in the pass — an empty
        pass would still inflate the event-pass gauges operators tune
        --event-debounce against."""
        registry = GaugeRegistry()
        manager, controller, store, clock = make_manager(
            None, registry=registry
        )
        revive_patch(store)
        store.delete("ScalableNodeGroup", "default", "g")
        assert manager.dirty_count() >= 1  # dirty survives the delete
        clock.advance(0.05)
        assert manager.run_event_pass() == 0
        assert controller.calls == 0
        assert registry.gauge(
            "runtime", "event_passes_total"
        ).get("manager", "-") is None, (
            "an all-deleted pass must not count"
        )

    def test_storm_coalesces_into_one_pass(self):
        """1k watch events inside one debounce window -> ONE pass, one
        reconcile (the event-storm contract the chaos suite replays at
        runtime scale)."""
        registry = GaugeRegistry()
        manager, controller, store, clock = make_manager(
            None, registry=registry
        )
        for _ in range(1000):
            revive_patch(store)
        assert manager.dirty_count() == 1  # same key: a set, not a log
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        assert controller.calls == 1
        assert registry.gauge(
            "runtime", "event_passes_total"
        ).get("manager", "-") == 1.0
        assert registry.gauge(
            "runtime", "event_pass_keys_total"
        ).get("manager", "-") == 1.0


class TestResyncBackstop:
    def test_tick_alone_converges_with_passes_suppressed(self):
        """Acceptance: with event passes suppressed (the thread dead,
        nobody calls run_event_pass), the tick must still pick up
        watch-marked work, run the tick-hook consumers, and revive a
        deactivated key — the backstop is a complete loop by itself."""
        hook_calls = []
        manager, controller, store, clock = make_manager(
            lambda: ValueError("poisoned"),
            tick_hook=lambda: hook_calls.append(1),
        )
        clock.advance(10_000)
        manager.reconcile_all()
        assert manager._due[KEY] == _NEVER
        controller.error_factory = None
        revive_patch(store)  # event marks due-now; NO pass ever runs
        assert manager.dirty_count() == 1
        manager.reconcile_all()  # the backstop tick handles it
        assert controller.calls == 2
        assert manager._due[KEY] == pytest.approx(clock.now + 60.0)
        assert len(hook_calls) == 2, "tick consumers fire per tick"

    def test_event_pass_skips_tick_consumers(self):
        """tick_hook (recovery warm-up counting, self-SLO evaluation)
        and gauge publication stay on the TICK cadence — an event storm
        must not multiply them."""
        hook_calls = []
        manager, controller, store, clock = make_manager(
            None, tick_hook=lambda: hook_calls.append(1)
        )
        revive_patch(store)
        clock.advance(0.05)
        assert manager.run_event_pass() == 1
        assert hook_calls == [], "event passes must not run tick hooks"
        manager.reconcile_all()
        assert len(hook_calls) == 1

    def test_event_pass_traces_distinctly(self):
        """A trace must distinguish event passes from backstop ticks:
        reconcile.event_pass vs reconcile.tick roots."""
        tracer = default_tracer()
        tracer.clear()
        manager, controller, store, clock = make_manager(None)
        revive_patch(store)
        clock.advance(0.05)
        manager.run_event_pass()
        manager.reconcile_all()
        names = {s["name"] for s in tracer.snapshot()}
        assert "reconcile.event_pass" in names
        assert "reconcile.tick" in names


class TestWireCompat:
    def test_off_by_default_builds_nothing(self):
        manager, controller, store, clock = make_manager(
            None, event_driven=False
        )
        revive_patch(store)
        assert manager.dirty_count() == 0, (
            "tick-paced mode must not track dirty keys"
        )
        assert manager.run_event_pass() == 0
        assert manager._event_worker is None
        # the watch event still marks due-now for the next tick (the
        # pre-PR semantics, byte for byte)
        assert manager._due[KEY] == 0.0

    def test_close_is_idempotent_and_safe_without_thread(self):
        manager, controller, store, clock = make_manager(None)
        manager.close()
        manager.close()
        assert manager._event_worker is None


class TestSelfPatchEcho:
    def test_own_status_patch_echo_is_suppressed(self):
        """The engine's own status patch fires a watch event for the
        key it just reconciled (synchronously, on the patching thread).
        That echo must neither re-stamp a just-retired e2e mark (it
        would measure the NEXT divergence from our own write) nor touch
        the due time nor mark the key dirty — while an identical event
        from any OTHER writer does all three."""
        manager, controller, store, clock = make_manager(None)
        manager._e2e_kinds.add("ScalableNodeGroup")
        tracer = default_tracer()
        tracer.drop_observed(KEY)
        with manager._dirty_lock:
            manager._dirty.clear()
        sng = store.get(*KEY)
        manager._due[KEY] = 123.0

        manager._patching.key = KEY  # what _finish sets around patch
        manager._on_event("Modified", sng)
        assert manager._due[KEY] == 123.0, "echo must not touch due"
        assert manager.dirty_count() == 0, "echo must not mark dirty"
        assert tracer.ack_observed(KEY) is None, "echo must not stamp"

        manager._patching.key = None  # any other writer's event
        manager._on_event("Modified", sng)
        assert manager._due[KEY] == 0.0
        assert manager.dirty_count() == 1
        assert tracer.ack_observed(KEY) is not None
        tracer.drop_observed(KEY)
