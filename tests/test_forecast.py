"""Predictive-scaling subsystem tests (karpenter_tpu/forecast/).

Pins the ISSUE's acceptance bar:
  * device == numpy forecast parity BIT-FOR-BIT per shape bucket
    (property sweep over models, masks, seasons, shapes);
  * ring-buffer wraparound / pruning / eviction correctness;
  * blend monotonicity — a forecast can only RAISE desired replicas,
    never lower them below the reactive decision;
  * all N HA series forecast in ONE coalesced device dispatch;
  * proactive lead — on a scripted ramp the forecast-enabled HA reaches
    target replicas >= 2 ticks before the reactive baseline, with an
    identical steady-state fixed point;
  * stale-metric bridge — a failed query reuses the last history sample
    (age-bounded) instead of dropping the row from the batch;
  * never-block — a failing forecast path degrades to reactive-only.
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.api.horizontalautoscaler import (
    Behavior,
    CrossVersionObjectReference,
    ForecastSpec,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.autoscaler import BatchAutoscaler
from karpenter_tpu.forecast import (
    FleetForecaster,
    MetricHistoryStore,
    models as M,
)
from karpenter_tpu.metrics.clients import MetricsClientFactory
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import decision as D
from karpenter_tpu.solver import SolverService
from karpenter_tpu.store import Store

SEED = 20260803


class FakeClock:
    def __init__(self, start=1_000_000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def random_forecast_inputs(rng, S, T):
    """Seeded, adversarially-shaped histories: ramps, seasonality,
    noise, gaps, mixed models, out-of-range seasons."""
    base = rng.uniform(0, 300, (S, 1)).astype(np.float32)
    ramp = rng.uniform(-2, 4, (S, 1)).astype(np.float32)
    ticks = np.arange(T, dtype=np.float32)[None, :]
    seasonal = (
        rng.uniform(0, 25, (S, 1)) * np.sin(ticks * 2 * np.pi / 8)
    ).astype(np.float32)
    noise = rng.normal(0, 4, (S, T)).astype(np.float32)
    values = (base + ramp * ticks * 10 + seasonal + noise).astype(
        np.float32
    )
    valid = rng.rand(S, T) > 0.3
    times = (
        (ticks - (T - 1)) * 10.0 + rng.uniform(-1, 1, (S, T))
    ).astype(np.float32)
    horizon = rng.uniform(10, 200, S).astype(np.float32)
    weights = np.power(
        np.float32(0.5), (-times) / horizon[:, None]
    ).astype(np.float32)
    return M.ForecastInputs(
        values=values,
        valid=valid,
        times=times,
        weights=weights,
        horizon=horizon,
        step_s=rng.uniform(0, 30, S).astype(np.float32),
        model=rng.choice([M.MODEL_LINEAR, M.MODEL_HOLT_WINTERS], S).astype(
            np.int32
        ),
        season=rng.choice([0, 1, 4, 8, 3 * T], S).astype(np.int32),
        alpha=rng.uniform(0.1, 1.0, S).astype(np.float32),
        beta=rng.uniform(0.05, 1.0, S).astype(np.float32),
        gamma=rng.uniform(0.05, 1.0, S).astype(np.float32),
    )


def assert_outputs_equal(a, b, context=""):
    for field in ("point", "sigma2", "n_valid"):
        x, y = np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        assert np.array_equal(x, y), (
            f"{context}: {field} differs bit-for-bit "
            f"(max |diff| {np.abs(x.astype(np.float64) - y.astype(np.float64)).max()})"
        )


class TestParity:
    """Device (jitted XLA) == numpy mirror, bit for bit — the fallback
    the degradation ladder serves must be indistinguishable."""

    def test_bit_for_bit_across_buckets(self):
        rng = np.random.RandomState(SEED)
        for S, T in [(1, 8), (5, 16), (33, 64), (128, 32)]:
            inputs = random_forecast_inputs(rng, S, T)
            dev = M.forecast_jit(inputs)
            host = M.forecast_numpy(inputs)
            assert_outputs_equal(dev, host, f"S={S} T={T}")

    def test_all_invalid_series_is_calm(self):
        rng = np.random.RandomState(SEED)
        inputs = random_forecast_inputs(rng, 4, 16)
        inputs.valid[:] = False
        dev = M.forecast_jit(inputs)
        host = M.forecast_numpy(inputs)
        assert_outputs_equal(dev, host, "all-invalid")
        assert np.all(np.asarray(dev.n_valid) == 0)
        assert np.all(np.asarray(dev.point) == 0.0)
        assert np.all(np.isfinite(np.asarray(dev.sigma2)))

    def test_padding_is_semantics_preserving(self):
        """Left-padding T and appending invalid S rows (what the service
        does to hit a shape bucket) must not move a single bit."""
        rng = np.random.RandomState(SEED + 1)
        inputs = random_forecast_inputs(rng, 6, 24)
        bare = M.forecast_numpy(inputs)
        padded = M.pad_forecast_inputs(inputs, 32)
        stacked = M.concat_forecast_inputs([padded], 8)
        padded_out = M.forecast_numpy(stacked)
        cropped = M.slice_forecast_outputs(padded_out, 0, 6)
        assert_outputs_equal(bare, cropped, "padding")

    def test_service_device_equals_direct_numpy(self):
        """Through the full service path (queue, bucketing, coalesced
        dispatch) the answer still equals the direct numpy mirror."""
        rng = np.random.RandomState(SEED + 2)
        inputs = random_forecast_inputs(rng, 9, 20)
        service = SolverService(backend="xla")
        try:
            out = service.forecast(inputs)
        finally:
            service.close()
        assert_outputs_equal(out, M.forecast_numpy(inputs), "service")

    def test_ramp_forecast_projects_ahead(self):
        """Sanity on the math itself: a clean linear ramp forecasts
        above its newest sample by roughly slope * horizon."""
        T = 32
        values = (10.0 + 2.0 * np.arange(T, dtype=np.float32))[None, :]
        times = ((np.arange(T, dtype=np.float32) - (T - 1)) * 10.0)[None, :]
        horizon = np.array([60.0], np.float32)
        weights = np.power(np.float32(0.5), (-times) / 60.0).astype(
            np.float32
        )
        inputs = M.ForecastInputs(
            values=values,
            valid=np.ones((1, T), bool),
            times=times,
            weights=weights,
            horizon=horizon,
            step_s=np.array([10.0], np.float32),
            model=np.array([M.MODEL_LINEAR], np.int32),
            season=np.zeros(1, np.int32),
            alpha=np.array([0.5], np.float32),
            beta=np.array([0.1], np.float32),
            gamma=np.array([0.3], np.float32),
        )
        point = float(M.forecast_numpy(inputs).point[0])
        newest = float(values[0, -1])
        # slope is 0.2/s, horizon 60s -> ~+12 over the newest sample
        assert newest + 8 < point < newest + 16


class TestHistoryStore:
    def test_wraparound_keeps_newest_in_order(self):
        store = MetricHistoryStore(capacity=8)
        for i in range(37):
            store.append(("ha", "ns", "x", 0), 100.0 + i, float(i))
        ts, vs = store.series(("ha", "ns", "x", 0))
        assert len(vs) == 8
        assert list(vs) == [float(i) for i in range(29, 37)]
        assert list(ts) == [100.0 + i for i in range(29, 37)]
        assert store.last(("ha", "ns", "x", 0)) == (136.0, 36.0)

    def test_non_finite_samples_dropped(self):
        store = MetricHistoryStore(capacity=4)
        store.append(("k",), 1.0, float("nan"))
        store.append(("k",), 2.0, float("inf"))
        store.append(("k",), 3.0, 7.0)
        assert store.count(("k",)) == 1

    def test_prune_by_prefix(self):
        store = MetricHistoryStore(capacity=4)
        store.append(("ha", "a", "x", 0), 1.0, 1.0)
        store.append(("ha", "a", "x", 1), 1.0, 1.0)
        store.append(("ha", "a", "y", 0), 1.0, 1.0)
        store.append(("q", "metric", ()), 1.0, 1.0)
        assert store.prune("ha", "a", "x") == 2
        assert store.count(("ha", "a", "x", 0)) == 0
        assert store.count(("ha", "a", "y", 0)) == 1
        assert store.count(("q", "metric", ())) == 1

    def test_bounded_series_eviction(self):
        store = MetricHistoryStore(capacity=4, max_series=3)
        for i in range(5):
            store.append(("s", i), float(i), 1.0)
        assert len(store) == 3
        # the oldest-touched series were evicted, the newest retained
        assert store.count(("s", 4)) == 1
        assert store.count(("s", 0)) == 0

    def test_seed_respects_series_bound(self):
        store = MetricHistoryStore(capacity=4, max_series=2)
        store.append(("q", "m", ()), 1.0, 1.0)
        store.append(("other",), 2.0, 1.0)
        assert store.seed(("ha", "ns", "x", 0), ("q", "m", ()))
        # seeding enforces the same bound append() does
        assert len(store) == 2

    def test_seed_copies_warm_pool(self):
        store = MetricHistoryStore(capacity=8)
        for i in range(5):
            store.append(("q", "m", ()), float(i), float(10 + i))
        assert store.seed(("ha", "ns", "x", 0), ("q", "m", ()))
        ts, vs = store.series(("ha", "ns", "x", 0))
        assert list(vs) == [10.0, 11.0, 12.0, 13.0, 14.0]
        # seeding never overwrites an existing series
        store.append(("ha", "ns", "y", 0), 9.0, 9.0)
        assert not store.seed(("ha", "ns", "y", 0), ("q", "m", ()))

    def test_matrix_right_aligned(self):
        store = MetricHistoryStore(capacity=6)
        for i in range(3):
            store.append(("k",), 100.0 + 10 * i, float(i))
        values, valid, times, step_s = store.matrix([("k",)], now=130.0)
        assert values.shape == (1, 6)
        assert list(valid[0]) == [False, False, False, True, True, True]
        assert list(values[0, 3:]) == [0.0, 1.0, 2.0]
        assert list(times[0, 3:]) == [-30.0, -20.0, -10.0]
        assert step_s[0] == pytest.approx(10.0)


def decision_inputs_with_forecast(rng, n=7, m=3):
    """A random reactive DecisionInputs plus a forecast overlay."""
    spec = rng.randint(1, 30, n).astype(np.int32)
    inputs = D.DecisionInputs(
        metric_value=rng.uniform(0, 100, (n, m)).astype(np.float32),
        target_value=rng.uniform(1, 20, (n, m)).astype(np.float32),
        target_type=rng.choice(
            [D.TYPE_VALUE, D.TYPE_AVERAGE_VALUE, D.TYPE_UTILIZATION], (n, m)
        ).astype(np.int32),
        metric_valid=rng.rand(n, m) > 0.2,
        spec_replicas=spec,
        status_replicas=spec,
        min_replicas=np.zeros(n, np.int32),
        max_replicas=np.full(n, 10_000, np.int32),
        up_window=np.zeros(n, np.int32),
        down_window=np.zeros(n, np.int32),
        up_policy=np.full(n, D.POLICY_MAX, np.int32),
        down_policy=np.full(n, D.POLICY_MAX, np.int32),
        last_scale_time=np.zeros(n, np.float32),
        has_last_scale=np.zeros(n, bool),
        now=np.float32(0.0),
        up_ptype=np.zeros((n, 1), np.int32),
        up_pvalue=np.zeros((n, 1), np.int32),
        up_pperiod=np.ones((n, 1), np.int32),
        up_pvalid=np.zeros((n, 1), bool),
        down_ptype=np.zeros((n, 1), np.int32),
        down_pvalue=np.zeros((n, 1), np.int32),
        down_pperiod=np.ones((n, 1), np.int32),
        down_pvalid=np.zeros((n, 1), bool),
    )
    forecast_value = rng.uniform(0, 150, (n, m)).astype(np.float32)
    forecast_valid = rng.rand(n, m) > 0.4
    return inputs, forecast_value, forecast_valid


class TestBlendMonotonicity:
    """The kernel property the spec's safety story rests on: forecasts
    can only RAISE desired replicas, never lower them below reactive."""

    def test_blend_never_lowers_desired(self):
        import dataclasses

        rng = np.random.RandomState(SEED)
        for _ in range(20):
            inputs, fv, fok = decision_inputs_with_forecast(rng)
            reactive = D.decide_jit(inputs)
            blended = D.decide_jit(
                dataclasses.replace(
                    inputs, forecast_value=fv, forecast_valid=fok
                )
            )
            assert np.all(
                np.asarray(blended.desired) >= np.asarray(reactive.desired)
            )
            assert np.all(
                np.asarray(blended.recommendation)
                >= np.asarray(reactive.recommendation)
            )

    def test_low_forecast_is_identity(self):
        """A forecast at-or-below the observed values changes nothing —
        scale-down stays purely reactive."""
        import dataclasses

        rng = np.random.RandomState(SEED + 1)
        for _ in range(10):
            inputs, _fv, fok = decision_inputs_with_forecast(rng)
            low = (np.asarray(inputs.metric_value) * 0.5).astype(np.float32)
            reactive = D.decide_jit(inputs)
            blended = D.decide_jit(
                dataclasses.replace(
                    inputs, forecast_value=low, forecast_valid=fok
                )
            )
            assert np.array_equal(
                np.asarray(blended.desired), np.asarray(reactive.desired)
            )

    def test_wire_codec_roundtrips_forecast_fields(self):
        """The sidecar's tensor framing carries (and tolerates the
        absence of) the new optional fields."""
        import dataclasses

        from karpenter_tpu.sidecar.codec import (
            pack_dataclass,
            unpack_dataclass,
        )

        rng = np.random.RandomState(SEED)
        inputs, fv, fok = decision_inputs_with_forecast(rng)
        with_fields = dataclasses.replace(
            inputs, forecast_value=fv, forecast_valid=fok
        )
        decoded, _ = unpack_dataclass(
            D.DecisionInputs, pack_dataclass(with_fields)
        )
        assert np.array_equal(decoded.forecast_value, fv)
        assert np.array_equal(decoded.forecast_valid, fok)
        legacy, _ = unpack_dataclass(
            D.DecisionInputs, pack_dataclass(inputs)
        )
        assert legacy.forecast_value is None
        assert legacy.forecast_valid is None


def forecast_ha(name="ha", target_name="g", spec=None, query=None):
    return HorizontalAutoscaler(
        metadata=ObjectMeta(name=name),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name=target_name
            ),
            min_replicas=1,
            max_replicas=10_000,
            metrics=[
                Metric(
                    prometheus=PrometheusMetricSource(
                        query=query
                        or f'karpenter_queue_length{{name="{name}"}}',
                        target=MetricTarget(type="AverageValue", value=4),
                    )
                )
            ],
            behavior=Behavior(forecast=spec),
        ),
    )


def fleet_world(n_has, spec):
    store = Store()
    registry = GaugeRegistry()
    gauge = registry.register("queue", "length")
    has = []
    for i in range(n_has):
        name = f"ha-{i}"
        gauge.set(name, "default", 8.0 + i)
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name=f"g-{i}"),
                spec=ScalableNodeGroupSpec(
                    replicas=2, type="FakeNodeGroup", id=f"g-{i}"
                ),
            )
        )
        ha = forecast_ha(name=name, target_name=f"g-{i}", spec=spec)
        store.create(ha)
        has.append(ha)
    return store, registry, gauge


class TestSingleDispatch:
    def test_all_series_one_coalesced_dispatch(self):
        """The acceptance criterion: N HAs' series forecast in ONE
        device dispatch per tick (stats.forecast_dispatches advances by
        exactly 1 once histories are warm)."""
        n = 9
        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=4
        )
        store, registry, gauge = fleet_world(n, spec)
        clock = FakeClock()
        service = SolverService(backend="xla")
        forecaster = FleetForecaster(
            forecast_fn=service.forecast,
            registry=registry,
            clock=clock,
            capacity=16,
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            decider=service.decide,
            forecaster=forecaster,
        )
        try:
            has = store.list("HorizontalAutoscaler")
            for _ in range(5):  # warm every series past min_samples
                errors = autoscaler.reconcile_batch(has)
                assert all(e is None for e in errors.values())
                clock.advance(10.0)
            before = service.stats.forecast_dispatches
            errors = autoscaler.reconcile_batch(has)
            assert all(e is None for e in errors.values())
            assert service.stats.forecast_dispatches == before + 1, (
                "all HA series must ride ONE coalesced forecast dispatch"
            )
            # and that one dispatch carried every series in the fleet
            assert service.stats.forecast_series >= n
        finally:
            service.close()

    def test_forecasting_condition_goes_true(self):
        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=3
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        service = SolverService(backend="xla")
        forecaster = FleetForecaster(
            forecast_fn=service.forecast, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            decider=service.decide,
            forecaster=forecaster,
        )
        try:
            ha = store.get("HorizontalAutoscaler", "default", "ha-0")
            autoscaler.reconcile_batch([ha])
            warming = ha.status_conditions().get(cond.FORECASTING)
            assert warming is not None and warming.status == cond.FALSE
            assert warming.reason == "ForecastWarmingUp"
            for _ in range(4):
                clock.advance(10.0)
                autoscaler.reconcile_batch([ha])
            active = ha.status_conditions().get(cond.FORECASTING)
            assert active.status == cond.TRUE
        finally:
            service.close()


class TestProactiveLead:
    def test_scripted_ramp_lead_and_fixed_point(self):
        """The seeded acceptance scenario: on a scripted ramp the
        forecast-enabled HA reaches target replicas >= 2 ticks before
        the reactive baseline, and both settle on the SAME fixed
        point."""
        from karpenter_tpu.simulate import simulate_forecast

        report = simulate_forecast(
            ticks=80,
            model="holt-winters",
            seed=SEED,
            backend="xla",
        )
        full = report["milestones"]["100%"]
        assert full["lead_ticks"] is not None and full["lead_ticks"] >= 2, (
            f"proactive lead below the bar: {report['milestones']}"
        )
        assert report["fixed_point"]["identical"], report["fixed_point"]
        assert report["forecast_dispatches"] > 0

    def test_linear_model_also_leads(self):
        from karpenter_tpu.simulate import simulate_forecast

        report = simulate_forecast(
            ticks=80, model="linear", seed=SEED + 1, backend="xla"
        )
        assert report["milestones"]["100%"]["lead_ticks"] >= 2
        assert report["fixed_point"]["identical"]


class TestStaleMetricBridge:
    def build(self, stale_max_age_s=60.0):
        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=4
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        service = SolverService(backend="xla")
        forecaster = FleetForecaster(
            forecast_fn=service.forecast,
            clock=clock,
            capacity=16,
            stale_max_age_s=stale_max_age_s,
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            decider=service.decide,
            forecaster=forecaster,
        )
        return store, registry, gauge, clock, service, autoscaler

    def test_failed_query_reuses_last_sample(self):
        store, registry, gauge, clock, service, autoscaler = self.build()
        try:
            ha = store.get("HorizontalAutoscaler", "default", "ha-0")
            gauge.set("ha-0", "default", 40.0)
            assert autoscaler.reconcile_batch([ha])[("default", "ha-0")] is None
            # the metric disappears (exporter restart): the row must
            # keep deciding on the last sample instead of erroring
            gauge.remove("ha-0", "default")
            clock.advance(10.0)
            error = autoscaler.reconcile_batch([ha])[("default", "ha-0")]
            assert error is None
            # ceil(40 / 4) = 10 — the decision used the stale sample
            assert ha.status.desired_replicas == 10
        finally:
            service.close()

    def test_stale_sample_ages_out(self):
        store, registry, gauge, clock, service, autoscaler = self.build(
            stale_max_age_s=30.0
        )
        try:
            ha = store.get("HorizontalAutoscaler", "default", "ha-0")
            assert autoscaler.reconcile_batch([ha])[("default", "ha-0")] is None
            gauge.remove("ha-0", "default")
            clock.advance(31.0)  # past the bound: the row must ERROR now
            error = autoscaler.reconcile_batch([ha])[("default", "ha-0")]
            assert error is not None
        finally:
            service.close()

    def test_without_forecaster_failure_still_errors(self):
        """Reactive-only runtimes keep the original posture: a failed
        query fails the row."""
        store, registry, gauge = fleet_world(1, None)
        clock = FakeClock()
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry), store, clock=clock
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        assert autoscaler.reconcile_batch([ha])[("default", "ha-0")] is None
        gauge.remove("ha-0", "default")
        assert (
            autoscaler.reconcile_batch([ha])[("default", "ha-0")]
            is not None
        )


class TestDegradation:
    def test_forecast_failure_degrades_to_reactive(self):
        """The never-block contract: a forecast path that RAISES (past
        every service degradation rung) costs the tick nothing but its
        proactivity."""

        def broken(_inputs):
            raise RuntimeError("device on fire")

        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=2
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        service = SolverService(backend="xla")
        forecaster = FleetForecaster(
            forecast_fn=broken, registry=registry, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            decider=service.decide,
            forecaster=forecaster,
        )
        try:
            ha = store.get("HorizontalAutoscaler", "default", "ha-0")
            gauge.set("ha-0", "default", 40.0)
            for _ in range(4):
                error = autoscaler.reconcile_batch([ha])[
                    ("default", "ha-0")
                ]
                assert error is None  # never blocks the reconcile
                clock.advance(10.0)
            # purely reactive decision: ceil(40/4)
            assert ha.status.desired_replicas == 10
            forecasting = ha.status_conditions().get(cond.FORECASTING)
            assert forecasting.status == cond.FALSE
            assert forecasting.reason == "ForecastUnavailable"
            disabled = registry.gauge("forecast", "disabled_total").get(
                "ha-0", "default"
            )
            assert disabled is not None and disabled >= 1
        finally:
            service.close()

    def test_skill_gate_disables_blend(self):
        """Consistently wrong forecasts push the skill EWMA under the
        spec floor and blending auto-disables with the structured
        reason."""
        spec = ForecastSpec(
            horizon_seconds=10.0, model="linear", min_samples=2,
            min_skill=0.9,
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()

        def wild(inputs):  # a forecaster that is always 10x too high
            out = M.forecast_numpy(inputs)
            return M.ForecastOutputs(
                point=out.point * 10.0 + 1000.0,
                sigma2=out.sigma2,
                n_valid=out.n_valid,
            )

        forecaster = FleetForecaster(
            forecast_fn=wild, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            decider=None,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        for _ in range(8):
            assert autoscaler.reconcile_batch([ha])[
                ("default", "ha-0")
            ] is None
            clock.advance(10.0)
        assert forecaster.skill("default", "ha-0") < 0.9
        forecasting = ha.status_conditions().get(cond.FORECASTING)
        assert forecasting.status == cond.FALSE
        assert forecasting.reason == "ForecastSkillDegraded"

    def test_skill_gate_recovers_via_shadow_predictions(self):
        """While gated, forecasts keep running in SHADOW (scored but
        not blended), so a forecaster that starts predicting well again
        lifts the skill EWMA back over the floor and blending
        re-enables — the gate is a pause, not a ratchet."""
        spec = ForecastSpec(
            horizon_seconds=10.0, model="linear", min_samples=2,
            min_skill=0.6,
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        mode = {"wild": True}

        def switchable(inputs):
            out = M.forecast_numpy(inputs)
            if mode["wild"]:
                return M.ForecastOutputs(
                    point=out.point * 10.0 + 1000.0,
                    sigma2=out.sigma2,
                    n_valid=out.n_valid,
                )
            return out

        forecaster = FleetForecaster(
            forecast_fn=switchable, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")

        def tick(n):
            for _ in range(n):
                assert autoscaler.reconcile_batch([ha])[
                    ("default", "ha-0")
                ] is None
                clock.advance(10.0)

        tick(10)
        assert forecaster.skill("default", "ha-0") < 0.6
        assert (
            ha.status_conditions().get(cond.FORECASTING).reason
            == "ForecastSkillDegraded"
        )
        mode["wild"] = False  # the forecaster heals
        tick(20)
        assert forecaster.skill("default", "ha-0") >= 0.6, (
            "shadow predictions must let the skill EWMA recover"
        )
        assert (
            ha.status_conditions().get(cond.FORECASTING).status
            == cond.TRUE
        )

    def test_query_observer_dedupes_shared_reads(self):
        """N autoscalers sharing one query read it N times per tick;
        the warm pool must keep ONE sample per tick or its apparent
        spacing (and any series seeded from it) would shrink N-fold."""
        from karpenter_tpu.metrics.types import Metric as MetricValue

        clock = FakeClock()
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, clock=clock, capacity=16
        )
        for tick in range(5):
            for _reader in range(3):  # three HAs share the query
                forecaster.observe_query(
                    MetricValue(name="q", labels={"name": "x"}, value=4.0)
                )
            clock.advance(10.0)
        from karpenter_tpu.forecast import query_key

        ts, _vs = forecaster.history.series(
            query_key("q", {"name": "x"})
        )
        assert len(ts) == 5
        assert list(np.diff(ts)) == [10.0] * 4

    def test_partially_warm_multimetric_ha_reports_active(self):
        """A second, freshly added metric must not flip the Forecasting
        condition to WarmingUp while the first metric's forecasts are
        actively blending."""
        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=3
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        for _ in range(4):  # warm metric 0 past min_samples
            assert autoscaler.reconcile_batch([ha])[
                ("default", "ha-0")
            ] is None
            clock.advance(10.0)
        assert (
            ha.status_conditions().get(cond.FORECASTING).status
            == cond.TRUE
        )
        # a second metric appears mid-life: series 1 is cold
        registry.gauge("queue", "length").set("extra", "default", 2.0)
        ha.spec.metrics.append(
            Metric(
                prometheus=PrometheusMetricSource(
                    query='karpenter_queue_length{name="extra"}',
                    target=MetricTarget(type="AverageValue", value=4),
                )
            )
        )
        assert autoscaler.reconcile_batch([ha])[("default", "ha-0")] is None
        forecasting = ha.status_conditions().get(cond.FORECASTING)
        assert forecasting.status == cond.TRUE, (
            "warm series still blend; the condition must say so"
        )

    def test_spec_removal_clears_condition(self):
        """Editing behavior.forecast OFF must drop the Forecasting
        condition from status — a frozen last value would keep
        reporting a posture nothing computes anymore."""
        spec = ForecastSpec(horizon_seconds=60.0, min_samples=2)
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        for _ in range(3):
            autoscaler.reconcile_batch([ha])
            clock.advance(10.0)
        assert ha.status_conditions().get(cond.FORECASTING) is not None
        ha.spec.behavior.forecast = None
        autoscaler.reconcile_batch([ha])
        assert ha.status_conditions().get(cond.FORECASTING) is None

    def test_skill_tolerates_near_zero_idle(self):
        """An accurate forecaster over a metric idling near zero with
        exporter noise must keep high skill — the error is normalized
        by the metric's TARGET scale, not the near-zero actual."""
        spec = ForecastSpec(
            horizon_seconds=10.0, model="linear", min_samples=2,
            min_skill=0.5,
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, clock=clock, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        rng = np.random.RandomState(SEED)
        for _ in range(12):  # overnight idle: ~0 with tiny noise
            gauge.set("ha-0", "default", abs(rng.normal(0.0, 0.01)))
            assert autoscaler.reconcile_batch([ha])[
                ("default", "ha-0")
            ] is None
            clock.advance(10.0)
        # |pred - actual| is a few hundredths against target scale 4:
        # skill must stay comfortably above the floor
        assert forecaster.skill("default", "ha-0") > 0.9

    def test_prune_forgets_deleted_autoscaler(self):
        spec = ForecastSpec(horizon_seconds=60.0, min_samples=2)
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, registry=registry, clock=clock,
            capacity=16,
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        for _ in range(3):
            autoscaler.reconcile_batch([ha])
            clock.advance(10.0)
        assert forecaster.history.count(("ha", "default", "ha-0", 0)) == 3
        forecaster.prune("default", "ha-0")
        assert forecaster.history.count(("ha", "default", "ha-0", 0)) == 0

    def test_ha_controller_on_deleted_prunes(self):
        from karpenter_tpu.controllers import HorizontalAutoscalerController

        spec = ForecastSpec(horizon_seconds=60.0, min_samples=2)
        store, registry, gauge = fleet_world(1, spec)
        forecaster = FleetForecaster(
            forecast_fn=M.forecast_numpy, capacity=16
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            forecaster=forecaster,
        )
        controller = HorizontalAutoscalerController(autoscaler)
        forecaster.history.append(("ha", "default", "ha-0", 0), 1.0, 1.0)
        controller.on_deleted(
            store.get("HorizontalAutoscaler", "default", "ha-0")
        )
        assert forecaster.history.count(("ha", "default", "ha-0", 0)) == 0


class TestSpecValidation:
    def test_bad_specs_rejected(self):
        bad = [
            ForecastSpec(horizon_seconds=0),
            ForecastSpec(model="prophet"),
            ForecastSpec(min_skill=1.5),
            ForecastSpec(season_seconds=-1),
            ForecastSpec(alpha=0.0),
            ForecastSpec(min_samples=1),
        ]
        for spec in bad:
            ha = forecast_ha(spec=spec)
            with pytest.raises(ValueError):
                ha.validate()

    def test_good_spec_roundtrips_yaml(self):
        from karpenter_tpu.api.serialization import (
            from_manifest,
            to_dict,
        )

        ha = forecast_ha(
            spec=ForecastSpec(
                horizon_seconds=120.0, model="holt-winters",
                season_seconds=3600.0,
            )
        )
        ha.validate()
        doc = to_dict(ha)
        assert doc["spec"]["behavior"]["forecast"]["horizonSeconds"] == 120.0
        back = from_manifest(doc)
        assert back.spec.behavior.forecast.model == "holt-winters"
        assert back.spec.behavior.forecast.season_seconds == 3600.0


class TestDistributionSurface:
    """The (point, sigma2) distribution face the cost subsystem reads
    as its risk input (docs/cost.md): fresh after a predict pass, None
    before one, and DROPPED once a series goes two horizons without a
    refresh — a broken metric must not pin an obsolete forecast spike
    as phantom demand forever."""

    def _world(self):
        spec = ForecastSpec(
            horizon_seconds=60.0, model="linear", min_samples=3
        )
        store, registry, gauge = fleet_world(1, spec)
        clock = FakeClock()
        forecaster = FleetForecaster(clock=clock, capacity=16)
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=clock,
            forecaster=forecaster,
        )
        return store, clock, forecaster, autoscaler

    def test_distribution_fresh_then_expires(self):
        store, clock, forecaster, autoscaler = self._world()
        ha = store.get("HorizontalAutoscaler", "default", "ha-0")
        assert forecaster.distribution("default", "ha-0", 0) is None
        for _ in range(5):
            autoscaler.reconcile_batch([ha])
            clock.advance(10.0)
        dist = forecaster.distribution("default", "ha-0", 0)
        assert dist is not None
        point, sigma2 = dist
        assert np.isfinite(point) and sigma2 >= 0.0
        # no refresh for two horizons (series stops forecasting):
        # the stale entry is dropped, not served
        clock.advance(2 * 60.0 + 1.0)
        assert forecaster.distribution("default", "ha-0", 0) is None
        assert ("default", "ha-0", 0) not in forecaster._dist
