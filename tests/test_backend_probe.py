"""Probe/fallback behavior of utils.backend under a hung or flaky tunnel.

The axon TPU tunnel's observed failure modes are (a) raised UNAVAILABLE,
which clears within seconds, and (b) a hard hang at client init, which can
last hours (it erased the round-1 and round-2 driver bench captures).
Control-plane entry points must fall back to CPU fast on (b); the
benchmark must instead wait out the outage on a long schedule. Both
policies live in probe_default_backend's hang_schedule parameter.

Reference behavior anchor: the reference trusts its accelerator runtime to
be present and has no analog — this subsystem exists because decisions
must keep flowing through an accelerator outage.
"""

from __future__ import annotations

import subprocess

import pytest

from karpenter_tpu.utils import backend


class _Hang:
    """subprocess.run stand-in that hangs N times, then succeeds."""

    def __init__(self, hangs: int, then: str = "tpu 1"):
        self.hangs = hangs
        self.then = then
        self.calls = 0

    def __call__(self, *a, timeout=None, **k):
        self.calls += 1
        if self.calls <= self.hangs:
            raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)
        out = lambda: None  # noqa: E731
        out.returncode = 0
        out.stdout = self.then
        out.stderr = ""
        return out


@pytest.fixture()
def no_sleep(monkeypatch):
    slept = []
    monkeypatch.setattr(
        "time.sleep", lambda s: slept.append(s), raising=True
    )
    return slept


def test_hang_aborts_short_retries_by_default(monkeypatch, no_sleep):
    """Entry-point policy: one hang => immediate CPU-fallback signal,
    without burning the remaining short retries (each costs timeout s)."""
    probe = _Hang(hangs=99)
    monkeypatch.setattr(subprocess, "run", probe)
    count, reason = backend.probe_default_backend(timeout=7.0, retries=2)
    assert count == 0
    assert "hung" in reason and "1 probe(s)" in reason
    assert probe.calls == 1
    assert no_sleep == []


def test_hang_schedule_waits_out_outage(monkeypatch, no_sleep):
    """Bench policy: a hang sleeps the next long delay and re-probes; the
    tunnel recovering on the final long retry yields a healthy result."""
    probe = _Hang(hangs=2)
    monkeypatch.setattr(subprocess, "run", probe)
    count, reason = backend.probe_default_backend(
        timeout=7.0, retries=2, hang_schedule=(300, 600)
    )
    assert (count, reason) == (1, "")
    assert probe.calls == 3
    assert no_sleep == [300.0, 600.0]


def test_hang_schedule_exhausted_fails_loud(monkeypatch, no_sleep):
    """All long retries hung too: the reason must say so, with the true
    probe count, so the driver JSON note is honest evidence."""
    probe = _Hang(hangs=99)
    monkeypatch.setattr(subprocess, "run", probe)
    count, reason = backend.probe_default_backend(
        timeout=7.0, retries=2, hang_schedule=(300,)
    )
    assert count == 0
    assert "hung" in reason and "2 probe(s)" in reason
    assert no_sleep == [300.0]


def test_raise_still_uses_short_backoff(monkeypatch, no_sleep):
    """A raised init error (not a hang) keeps the short exponential
    backoff; hang_schedule is not consumed."""

    calls = {"n": 0}

    def raises(*a, timeout=None, **k):
        calls["n"] += 1
        out = lambda: None  # noqa: E731
        out.returncode = 1
        out.stdout = ""
        out.stderr = "RuntimeError: UNAVAILABLE: tunnel reset"
        return out

    monkeypatch.setattr(subprocess, "run", raises)
    count, reason = backend.probe_default_backend(
        timeout=7.0, retries=2, hang_schedule=(300, 600)
    )
    assert count == 0
    assert "UNAVAILABLE" in reason and "3 probe(s)" in reason
    assert calls["n"] == 3
    assert no_sleep == [5.0, 10.0]  # short backoff only, no long delays


def test_hang_then_raise_then_recover(monkeypatch, no_sleep):
    """After a long hang-retry the short-retry budget is fresh: hang,
    long sleep, raise, short sleep, success."""

    seq = ["hang", "raise", "ok"]

    def flaky(*a, timeout=None, **k):
        step = seq.pop(0)
        if step == "hang":
            raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)
        out = lambda: None  # noqa: E731
        out.returncode = 0 if step == "ok" else 1
        out.stdout = "tpu 1" if step == "ok" else ""
        out.stderr = "" if step == "ok" else "UNAVAILABLE"
        return out

    monkeypatch.setattr(subprocess, "run", flaky)
    count, reason = backend.probe_default_backend(
        timeout=7.0, retries=2, hang_schedule=(120,)
    )
    assert (count, reason) == (1, "")
    assert seq == []
    assert no_sleep == [120.0, 5.0]
