"""Conformance sweep of tests/fake_apiserver.py against the documented
kube-apiserver contract.

The fake plays the envtest role (reference:
pkg/test/environment/local.go:53-157 boots a REAL apiserver); everything
KubeStore's hardening is validated against runs through it, so the fake
itself must be held to the apiserver's documented semantics — otherwise
the hardening is only proven against the builder's own invention. Each
case cites the contract it checks (Kubernetes API Concepts: "Resource
versions", "Efficient detection of changes", "Retrieving large results
sets in chunks", "410 Gone responses").

The final cases fuzz randomized write sequences against a live KubeStore
mirror — the property the whole informer stack rests on: after any
op sequence plus quiescence, mirror state == server state.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from karpenter_tpu.store.kube import KubeClient, KubeStore
from tests.fake_apiserver import FakeApiServer


def pod_doc(name, node=""):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"nodeName": node},
    }


@pytest.fixture()
def server():
    fake = FakeApiServer()
    fake.start()
    yield fake
    fake.stop()


@pytest.fixture()
def client(server):
    return KubeClient(base_url=f"http://127.0.0.1:{server.port}")


def http_get(server, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}{path}"
    ) as response:
        return json.loads(response.read())


class TestResourceVersions:
    """API Concepts 'Resource versions': every write produces a new,
    strictly-greater resourceVersion; versions are never reused."""

    def test_writes_are_strictly_monotonic(self, server):
        seen = []
        for i in range(20):
            doc = server.put_object("pods", pod_doc(f"p{i}"))
            seen.append(int(doc["metadata"]["resourceVersion"]))
        for i in range(10):
            doc = server.put_object(
                "pods", pod_doc(f"p{i}", node="n"), event="MODIFIED"
            )
            seen.append(int(doc["metadata"]["resourceVersion"]))
        assert seen == sorted(seen)
        assert len(set(seen)) == len(seen)  # no reuse

    def test_delete_bumps_rv_and_event_carries_it(self, server):
        created = server.put_object("pods", pod_doc("victim"))
        created_rv = int(created["metadata"]["resourceVersion"])
        deleted = server.delete_object("pods", "default", "victim")
        # API Concepts: a delete is a write like any other — the DELETED
        # watch event carries the object's final state AT the deletion's
        # (new) resourceVersion, so clients can advance their watermark
        assert int(deleted["metadata"]["resourceVersion"]) > created_rv
        rv, plural, event = server._history[-1]
        assert event["type"] == "DELETED"
        assert int(event["object"]["metadata"]["resourceVersion"]) == rv

    def test_list_rv_covers_every_item(self, server):
        for i in range(5):
            server.put_object("pods", pod_doc(f"p{i}"))
        payload = http_get(server, "/api/v1/pods")
        collection_rv = int(payload["metadata"]["resourceVersion"])
        for item in payload["items"]:
            assert int(item["metadata"]["resourceVersion"]) <= collection_rv


class TestChunkedList:
    """API Concepts 'Retrieving large results sets in chunks': all pages
    of one paginated LIST are served from a consistent snapshot at the
    first page's resourceVersion; an expired continue token is 410."""

    def test_pages_are_a_consistent_snapshot(self, server):
        for i in range(10):
            server.put_object("pods", pod_doc(f"p{i:02d}"))
        first = http_get(server, "/api/v1/pods?limit=4")
        snapshot_rv = first["metadata"]["resourceVersion"]
        token = first["metadata"]["continue"]
        # concurrent writes between pages must not shift pagination
        server.put_object("pods", pod_doc("p-concurrent-a"))
        server.delete_object("pods", "default", "p07")
        second = http_get(server, f"/api/v1/pods?limit=4&continue={token}")
        assert second["metadata"]["resourceVersion"] == snapshot_rv
        third = http_get(
            server,
            f"/api/v1/pods?limit=4&continue={second['metadata']['continue']}",
        )
        names = [
            item["metadata"]["name"]
            for payload in (first, second, third)
            for item in payload["items"]
        ]
        # exactly the 10 objects of the snapshot: no skip, no duplicate,
        # no bleed-through of the concurrent create/delete
        assert names == [f"p{i:02d}" for i in range(10)]
        assert "continue" not in third["metadata"]

    def test_expired_continue_token_is_410(self, server):
        for i in range(6):
            server.put_object("pods", pod_doc(f"p{i}"))
        first = http_get(server, "/api/v1/pods?limit=2")
        token = first["metadata"]["continue"]
        # churn through enough new paginations to evict the snapshot
        for _ in range(9):
            http_get(server, "/api/v1/pods?limit=2")
        with pytest.raises(urllib.request.HTTPError) as excinfo:
            http_get(server, f"/api/v1/pods?limit=2&continue={token}")
        assert excinfo.value.code == 410
        body = json.loads(excinfo.value.read())
        assert body["reason"] == "Expired"

    def test_client_list_spans_pages_coherently(self, client, server):
        for i in range(12):
            server.put_object("pods", pod_doc(f"p{i:02d}"))
        client.list_chunk_size = 5
        objs, rv = client.list("Pod")
        assert sorted(o.metadata.name for o in objs) == [
            f"p{i:02d}" for i in range(12)
        ]
        assert int(rv) >= 12


class TestWatchContract:
    """API Concepts 'Efficient detection of changes': a watch from rv R
    delivers exactly the events AFTER R (including DELETED), in order;
    a watch from before the server's history window gets an in-stream
    ERROR event carrying a 410 Status, then the stream ends."""

    def _collect(self, client, since, idle=1.0):
        import threading

        client.timeout = idle  # idle socket timeout ends the one pass
        events = []

        def handler(etype, obj):
            events.append(
                (etype, obj.metadata.name, obj.metadata.resource_version)
            )

        client.watch("Pod", str(since), handler, threading.Event())
        return events

    def test_replay_excludes_seen_and_includes_deletes(self, client, server):
        server.put_object("pods", pod_doc("a"))
        seen = server.put_object("pods", pod_doc("b"))
        since = int(seen["metadata"]["resourceVersion"])
        server.put_object("pods", pod_doc("c"))
        server.delete_object("pods", "default", "a")

        from karpenter_tpu.store.store import ADDED, DELETED

        events = self._collect(client, since)
        names = [(etype, name) for etype, name, _ in events]
        assert (ADDED, "c") in names
        # the DELETED event must be replayed: an object-state replay
        # would lose it and the resumed informer would keep 'a' forever
        assert (DELETED, "a") in names
        assert all(name != "b" for _, name in names)  # nothing <= since
        rvs = [int(rv) for _, _, rv in events]
        assert rvs == sorted(rvs) and all(rv > since for rv in rvs)

    def test_too_old_rv_is_in_stream_error_410(self, server):
        fake = FakeApiServer(history_limit=4)
        fake.start()
        try:
            client = KubeClient(base_url=f"http://127.0.0.1:{fake.port}")
            first = fake.put_object("pods", pod_doc("p0"))
            horizon_rv = int(first["metadata"]["resourceVersion"])
            for i in range(1, 10):  # push p0's event past the window
                fake.put_object("pods", pod_doc(f"p{i}"))
            import threading

            from karpenter_tpu.store.store import ConflictError

            client.timeout = 1.0
            with pytest.raises(ConflictError, match="410"):
                client.watch(
                    "Pod", str(horizon_rv), lambda *a: None,
                    threading.Event(),
                )
        finally:
            fake.stop()

    def test_fresh_watch_rv_zero_serves_current_state(self, client, server):
        server.put_object("pods", pod_doc("x"))
        server.put_object("pods", pod_doc("y"))
        server.delete_object("pods", "default", "x")
        from karpenter_tpu.store.store import ADDED

        events = self._collect(client, 0)
        # rv=0 means "any point": current state only, no tombstones
        assert [(t, n) for t, n, _ in events] == [(ADDED, "y")]


class TestMirrorFuzz:
    """The informer-stack property everything rests on: after ANY write
    sequence plus quiescence, the KubeStore mirror equals server state —
    including sequences that cross the watch history horizon (forcing
    the 410 -> relist path KubeStore._watch_loop implements)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_ops_converge(self, seed):
        import numpy as np

        rng = np.random.default_rng(seed)
        fake = FakeApiServer(history_limit=16)  # tiny window: force 410s
        fake.start()
        store = None
        try:
            store = KubeStore(
                KubeClient(base_url=f"http://127.0.0.1:{fake.port}"),
                watch_kinds=("Pod",),
            )
            live = set()
            for step in range(120):
                op = rng.random()
                if op < 0.6 or not live:
                    name = f"p{step}"
                    fake.put_object("pods", pod_doc(name))
                    live.add(name)
                elif op < 0.8:
                    name = sorted(live)[
                        int(rng.integers(0, len(live)))
                    ]
                    fake.put_object(
                        "pods", pod_doc(name, node=f"n{step}"),
                        event="MODIFIED",
                    )
                else:
                    name = sorted(live)[
                        int(rng.integers(0, len(live)))
                    ]
                    fake.delete_object("pods", "default", name)
                    live.discard(name)
            deadline = time.time() + 10.0
            while time.time() < deadline:
                mirrored = {
                    p.metadata.name for p in store.list("Pod")
                }
                if mirrored == live:
                    break
                time.sleep(0.1)
            assert {
                p.metadata.name for p in store.list("Pod")
            } == live
        finally:
            if store is not None:
                store.close()
            fake.stop()


class TestExpiredStreamShape:
    def test_410_stream_terminates_cleanly(self):
        """The expired-watch ERROR event arrives in a chunked body that
        ENDS (terminal chunk + close): consumers treating stream-EOF as
        the relist signal must not hang (API Concepts: the server closes
        the watch after the 410 Status event)."""
        fake = FakeApiServer(history_limit=0)  # zero window: always 410
        fake.start()
        try:
            fake.put_object("pods", pod_doc("p0"))
            fake.put_object("pods", pod_doc("p1"))
            req = urllib.request.Request(
                f"http://127.0.0.1:{fake.port}"
                "/api/v1/pods?watch=1&resourceVersion=1"
            )
            with urllib.request.urlopen(req, timeout=3.0) as response:
                body = response.read()  # must EOF, not block
            event = json.loads(body.decode().strip())
            assert event["type"] == "ERROR"
            assert event["object"]["code"] == 410
            assert event["object"]["reason"] == "Expired"
        finally:
            fake.stop()

    def test_zero_history_limit_is_honored(self):
        """history_limit=0 models a zero-length watch window — it must
        not silently fall back to the default."""
        fake = FakeApiServer(history_limit=0)
        assert fake._history_limit == 0
