"""SDK binding tests: boto3 / google-cloud adapters against recorded
call/response shapes, with the SDK modules stubbed into sys.modules.

reference: pkg/cloudprovider/aws/factory.go:41-76 — the reference builds a
live session at factory construction; its tests run against the fake
factory instead. Here the binding layer itself is under test: call-shape
translation, error taxonomy mapping, region discovery, and automatic
selection (KARPENTER_CLOUD_PROVIDER=aws constructs a bound factory with no
injection when the SDK is importable).
"""

from __future__ import annotations

import importlib.machinery
import sys
import types

import pytest

from karpenter_tpu.cloudprovider import Options, node_template_from_raw
from karpenter_tpu.cloudprovider.aws import (
    AWSAPIError,
    AWSFactory,
    transient_error,
)
from karpenter_tpu.controllers.errors import RetryableError


# ---------------------------------------------------------------------------
# boto3 / botocore stubs
# ---------------------------------------------------------------------------


class _ClientError(Exception):
    def __init__(self, code, message="boom"):
        super().__init__(message)
        self.response = {"Error": {"Code": code, "Message": message}}


# mirror botocore's hierarchy: leaf connection errors subclass
# ConnectionError / HTTPClientError, which is what _translate_call catches
class _ConnectionError(Exception):
    pass


class _HTTPClientError(Exception):
    pass


class _EndpointConnectionError(_ConnectionError):
    pass


class _ConnectionClosedError(_ConnectionError):
    pass


class _ConnectTimeoutError(_ConnectionError):
    pass


class _ReadTimeoutError(_HTTPClientError):
    pass


class _RecordedClient:
    """Duck-typed boto3 service client: canned responses, recorded calls."""

    def __init__(self, responses=None):
        self.responses = responses or {}
        self.calls = []

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(**kwargs):
            self.calls.append((name, kwargs))
            result = self.responses.get(name, {})
            if isinstance(result, Exception):
                raise result
            return result

        return call


class _FakeSession:
    def __init__(self, clients, region_name=None):
        self._clients = clients
        self.region_name = region_name
        self.client_calls = []

    def client(self, service, region_name=None):
        self.client_calls.append((service, region_name))
        return self._clients.get(service, _RecordedClient())


@pytest.fixture()
def boto3_stub(monkeypatch):
    """Install fake boto3/botocore into sys.modules and reset the binding
    cache around the test. Yields a dict the test fills with per-service
    _RecordedClients before the first bind."""
    from karpenter_tpu.cloudprovider import aws_sdk

    clients = {}
    boto3_mod = types.ModuleType("boto3")
    boto3_mod.__spec__ = importlib.machinery.ModuleSpec("boto3", None)
    session_mod = types.ModuleType("boto3.session")
    session_mod.Session = lambda: _FakeSession(clients)
    boto3_mod.session = session_mod
    botocore_mod = types.ModuleType("botocore")
    botocore_mod.__spec__ = importlib.machinery.ModuleSpec("botocore", None)
    exceptions_mod = types.ModuleType("botocore.exceptions")
    exceptions_mod.ClientError = _ClientError
    exceptions_mod.ConnectionError = _ConnectionError
    exceptions_mod.HTTPClientError = _HTTPClientError
    exceptions_mod.EndpointConnectionError = _EndpointConnectionError
    exceptions_mod.ConnectionClosedError = _ConnectionClosedError
    exceptions_mod.ConnectTimeoutError = _ConnectTimeoutError
    exceptions_mod.ReadTimeoutError = _ReadTimeoutError
    botocore_mod.exceptions = exceptions_mod
    monkeypatch.setitem(sys.modules, "boto3", boto3_mod)
    monkeypatch.setitem(sys.modules, "boto3.session", session_mod)
    monkeypatch.setitem(sys.modules, "botocore", botocore_mod)
    monkeypatch.setitem(sys.modules, "botocore.exceptions", exceptions_mod)
    monkeypatch.setenv("AWS_REGION", "us-west-2")
    aws_sdk.reset_binding_cache()
    yield clients
    aws_sdk.reset_binding_cache()


# ---------------------------------------------------------------------------
# Binding selection
# ---------------------------------------------------------------------------


def test_no_sdk_binds_nothing_and_factory_guides(monkeypatch):
    """With boto3 unavailable (stubbed — don't assert host properties),
    bind degrades to None; and DIRECT factory construction keeps the
    guidance stubs regardless of SDK presence (autobind is registry-only,
    so unit tests never build live cloud clients)."""
    from karpenter_tpu.cloudprovider import aws_sdk

    monkeypatch.setattr(aws_sdk, "sdk_available", lambda: False)
    aws_sdk.reset_binding_cache()
    assert aws_sdk.bind("autoscaling") is None
    factory = AWSFactory(Options())
    with pytest.raises(RuntimeError, match="no autoscaling API client"):
        factory.autoscaling_client.update_auto_scaling_group(name="x")


def test_direct_construction_never_autobinds(boto3_stub):
    """Even with a bindable SDK ambient, AWSFactory() without
    sdk_autobind must keep the guidance stubs."""
    factory = AWSFactory(Options())
    with pytest.raises(RuntimeError, match="no autoscaling API client"):
        factory.autoscaling_client.update_auto_scaling_group(name="x")


def test_env_selected_aws_factory_binds_sdk_without_injection(
    boto3_stub, monkeypatch
):
    """VERDICT r2 done-criterion: KARPENTER_CLOUD_PROVIDER=aws constructs
    a working (SDK-bound) factory with no injected clients."""
    from karpenter_tpu.cloudprovider import aws_sdk, registry

    monkeypatch.setenv("KARPENTER_CLOUD_PROVIDER", "aws")
    factory = registry.new_factory(Options())
    assert isinstance(
        factory.autoscaling_client, aws_sdk.Boto3AutoscalingClient
    )
    assert isinstance(factory.eks_client, aws_sdk.Boto3EKSClient)
    assert isinstance(factory.sqs_client, aws_sdk.Boto3SQSClient)


def test_region_resolution_order(boto3_stub, monkeypatch):
    from karpenter_tpu.cloudprovider import aws_sdk

    # env wins
    assert aws_sdk.resolve_region() == "us-west-2"
    # session config is next
    monkeypatch.delenv("AWS_REGION")
    session = _FakeSession({}, region_name="eu-central-1")
    assert aws_sdk.resolve_region(session) == "eu-central-1"
    # IMDS is last; stubbed unreachable (a real EC2 host would answer)
    monkeypatch.setattr(aws_sdk, "_imds_region", lambda: None)
    assert aws_sdk.resolve_region(_FakeSession({})) is None


def test_unresolvable_region_leaves_clients_unbound(boto3_stub, monkeypatch):
    from karpenter_tpu.cloudprovider import aws_sdk

    monkeypatch.delenv("AWS_REGION")
    monkeypatch.setattr(aws_sdk, "_imds_region", lambda: None)
    aws_sdk.reset_binding_cache()
    assert aws_sdk.bind("autoscaling") is None


# ---------------------------------------------------------------------------
# Call-shape translation
# ---------------------------------------------------------------------------


def test_asg_describe_shape_translation(boto3_stub):
    from karpenter_tpu.cloudprovider import aws_sdk

    asg = _RecordedClient(
        {
            "describe_auto_scaling_groups": {
                "AutoScalingGroups": [
                    {
                        "AutoScalingGroupName": "web",
                        "DesiredCapacity": 3,
                        "Instances": [
                            {
                                "HealthStatus": "Healthy",
                                "LifecycleState": "InService",
                            },
                            {
                                "HealthStatus": "Unhealthy",
                                "LifecycleState": "Terminating",
                            },
                        ],
                        "Tags": [{"Key": "team", "Value": "infra"}],
                    }
                ]
            }
        }
    )
    boto3_stub["autoscaling"] = asg
    client = aws_sdk.bind("autoscaling")
    groups = client.describe_auto_scaling_groups(["web"], 1)
    assert asg.calls[0] == (
        "describe_auto_scaling_groups",
        {"AutoScalingGroupNames": ["web"], "MaxRecords": 1},
    )
    assert groups[0]["desired_capacity"] == 3
    assert groups[0]["instances"] == [
        {"health_status": "Healthy", "lifecycle_state": "InService"},
        {"health_status": "Unhealthy", "lifecycle_state": "Terminating"},
    ]

    client.update_auto_scaling_group(name="web", desired_capacity=5)
    assert asg.calls[-1] == (
        "update_auto_scaling_group",
        {"AutoScalingGroupName": "web", "DesiredCapacity": 5},
    )


def test_asg_node_template_from_tags_and_instance_type(boto3_stub):
    """Scale-from-zero: mixed-policy override type sized via
    DescribeInstanceTypes; labels/taints from the cluster-autoscaler
    node-template tag convention; parses through node_template_from_raw."""
    from karpenter_tpu.cloudprovider import aws_sdk

    boto3_stub["autoscaling"] = _RecordedClient(
        {
            "describe_auto_scaling_groups": {
                "AutoScalingGroups": [
                    {
                        "AutoScalingGroupName": "gpu",
                        "MixedInstancesPolicy": {
                            "LaunchTemplate": {
                                "Overrides": [{"InstanceType": "m5.xlarge"}]
                            }
                        },
                        "Tags": [
                            {
                                "Key": "k8s.io/cluster-autoscaler/"
                                "node-template/label/pool",
                                "Value": "batch",
                            },
                            {
                                "Key": "k8s.io/cluster-autoscaler/"
                                "node-template/taint/dedicated",
                                "Value": "batch:NoSchedule",
                            },
                        ],
                    }
                ]
            }
        }
    )
    boto3_stub["ec2"] = _RecordedClient(
        {
            "describe_instance_types": {
                "InstanceTypes": [
                    {
                        "VCpuInfo": {"DefaultVCpus": 4},
                        "MemoryInfo": {"SizeInMiB": 16384},
                    }
                ]
            }
        }
    )
    client = aws_sdk.bind("autoscaling")
    raw = client.describe_node_template("gpu")
    template = node_template_from_raw(raw)
    assert str(template.allocatable["cpu"]) == "4"
    assert template.allocatable["memory"].to_float() == 16384 * 1024 * 1024
    assert template.labels["pool"] == "batch"
    assert template.labels["node.kubernetes.io/instance-type"] == "m5.xlarge"
    assert template.taints[0].key == "dedicated"
    assert template.taints[0].value == "batch"
    assert template.taints[0].effect == "NoSchedule"


def test_eks_adapter_shapes(boto3_stub):
    from karpenter_tpu.cloudprovider import aws_sdk

    eks = _RecordedClient(
        {
            "describe_nodegroup": {
                "nodegroup": {
                    "instanceTypes": ["c5.large"],
                    "labels": {"role": "worker"},
                    "taints": [
                        {
                            "key": "gpu",
                            "value": "true",
                            "effect": "NO_SCHEDULE",
                        }
                    ],
                }
            }
        }
    )
    boto3_stub["eks"] = eks
    boto3_stub["ec2"] = _RecordedClient(
        {
            "describe_instance_types": {
                "InstanceTypes": [
                    {
                        "VCpuInfo": {"DefaultVCpus": 2},
                        "MemoryInfo": {"SizeInMiB": 4096},
                    }
                ]
            }
        }
    )
    client = aws_sdk.bind("eks")
    client.update_nodegroup_config(
        cluster_name="prod", nodegroup_name="pool-a", desired_size=7
    )
    assert eks.calls[0] == (
        "update_nodegroup_config",
        {
            "clusterName": "prod",
            "nodegroupName": "pool-a",
            "scalingConfig": {"desiredSize": 7},
        },
    )
    template = node_template_from_raw(
        client.describe_node_template("prod", "pool-a")
    )
    assert str(template.allocatable["cpu"]) == "2"
    assert template.labels["role"] == "worker"
    # EKS enum dialect translated to core/v1 spelling
    assert template.taints[0].effect == "NoSchedule"


def test_sqs_adapter_shapes(boto3_stub):
    from karpenter_tpu.cloudprovider import aws_sdk

    sqs = _RecordedClient(
        {
            "get_queue_url": {"QueueUrl": "https://sqs/q"},
            "get_queue_attributes": {
                "Attributes": {"ApproximateNumberOfMessages": "12"}
            },
            "receive_message": {
                "Messages": [{"Attributes": {"SentTimestamp": "123"}}]
            },
        }
    )
    boto3_stub["sqs"] = sqs
    client = aws_sdk.bind("sqs")
    assert client.get_queue_url("q", "123456789012") == "https://sqs/q"
    assert sqs.calls[0] == (
        "get_queue_url",
        {"QueueName": "q", "QueueOwnerAWSAccountId": "123456789012"},
    )
    attributes = client.get_queue_attributes(
        "https://sqs/q", ["ApproximateNumberOfMessages"]
    )
    assert attributes == {"ApproximateNumberOfMessages": "12"}
    messages = client.receive_message(
        queue_url="https://sqs/q",
        attribute_names=["SentTimestamp"],
        max_number_of_messages=10,
        visibility_timeout=0,
    )
    assert messages[0]["Attributes"]["SentTimestamp"] == "123"


# ---------------------------------------------------------------------------
# Error taxonomy translation
# ---------------------------------------------------------------------------


def test_botocore_error_translation(boto3_stub):
    from karpenter_tpu.cloudprovider import aws_sdk

    # throttling: code-classified retryable
    boto3_stub["autoscaling"] = _RecordedClient(
        {"update_auto_scaling_group": _ClientError("Throttling")}
    )
    client = aws_sdk.bind("autoscaling")
    with pytest.raises(AWSAPIError) as excinfo:
        client.update_auto_scaling_group(name="x", desired_capacity=1)
    assert excinfo.value.code == "Throttling"
    assert excinfo.value.retryable
    wrapped = transient_error(excinfo.value)
    assert isinstance(wrapped, RetryableError) and wrapped.retryable

    # validation: terminal
    aws_sdk.reset_binding_cache()
    boto3_stub["autoscaling"] = _RecordedClient(
        {"update_auto_scaling_group": _ClientError("ValidationError")}
    )
    client = aws_sdk.bind("autoscaling")
    with pytest.raises(AWSAPIError) as excinfo:
        client.update_auto_scaling_group(name="x", desired_capacity=1)
    assert not excinfo.value.retryable
    assert not transient_error(excinfo.value).retryable

    # connection-level failures: no code, forced retryable — including
    # leaf classes only reachable via the ConnectionError/HTTPClientError
    # base classes (ConnectionClosedError was classified terminal before)
    for failure in (
        _EndpointConnectionError("no route"),
        _ConnectionClosedError("reset by peer"),
        _ReadTimeoutError("read timed out"),
    ):
        aws_sdk.reset_binding_cache()
        boto3_stub["autoscaling"] = _RecordedClient(
            {"describe_auto_scaling_groups": failure}
        )
        client = aws_sdk.bind("autoscaling")
        with pytest.raises(AWSAPIError) as excinfo:
            client.describe_auto_scaling_groups(["x"], 1)
        assert excinfo.value.retryable and excinfo.value.code == ""


def test_unknown_seam_raises_but_bad_region_degrades(boto3_stub, monkeypatch):
    """bind('bogus') is a programming error (raises); a ValueError from
    INSIDE the SDK (botocore InvalidRegionError subclasses ValueError)
    must degrade to None, not crash factory construction."""
    from karpenter_tpu.cloudprovider import aws_sdk

    with pytest.raises(ValueError, match="unknown AWS service seam"):
        aws_sdk.bind("bogus")

    class _InvalidRegionSession:
        region_name = "bad region!"

        def client(self, service, region_name=None):
            raise ValueError(f"Provided region_name '{region_name}' doesn't "
                             "match a supported format.")

    sys.modules["boto3"].session.Session = _InvalidRegionSession
    aws_sdk.reset_binding_cache()
    assert aws_sdk.bind("autoscaling") is None


def test_asg_template_launch_template_name_fallback(boto3_stub):
    """Name-only LaunchTemplateSpecification (a shape AWS returns) must
    query by LaunchTemplateName, never pass LaunchTemplateId=None."""
    from karpenter_tpu.cloudprovider import aws_sdk

    boto3_stub["autoscaling"] = _RecordedClient(
        {
            "describe_auto_scaling_groups": {
                "AutoScalingGroups": [
                    {
                        "AutoScalingGroupName": "named",
                        "LaunchTemplate": {
                            "LaunchTemplateName": "web-lt",
                            "Version": "3",
                        },
                    }
                ]
            }
        }
    )
    ec2 = _RecordedClient(
        {
            "describe_launch_template_versions": {
                "LaunchTemplateVersions": [
                    {"LaunchTemplateData": {"InstanceType": "t3.large"}}
                ]
            },
            "describe_instance_types": {
                "InstanceTypes": [{"VCpuInfo": {"DefaultVCpus": 2}}]
            },
        }
    )
    boto3_stub["ec2"] = ec2
    raw = aws_sdk.bind("autoscaling").describe_node_template("named")
    assert ec2.calls[0] == (
        "describe_launch_template_versions",
        {"LaunchTemplateName": "web-lt", "Versions": ["3"]},
    )
    assert raw["labels"]["node.kubernetes.io/instance-type"] == "t3.large"

    # spec with neither id nor name: no declared shape, not a crash
    aws_sdk.reset_binding_cache()
    boto3_stub["autoscaling"] = _RecordedClient(
        {
            "describe_auto_scaling_groups": {
                "AutoScalingGroups": [
                    {"AutoScalingGroupName": "bare", "LaunchTemplate": {}}
                ]
            }
        }
    )
    assert aws_sdk.bind("autoscaling").describe_node_template("bare") is None


# ---------------------------------------------------------------------------
# GKE container binding (google.api_core is baked in; container_v1 is not,
# so the adapter is tested against fake transport clients raising REAL
# google.api_core exceptions)
# ---------------------------------------------------------------------------


class _FakeOperation:
    def __init__(self, name, status_name, target_link):
        self.name = name
        self.status = types.SimpleNamespace(name=status_name)
        self.target_link = target_link


class _FakeGKEClient:
    def __init__(self, operations=(), node_pool=None, fail=None):
        self.operations = list(operations)
        self.node_pool = node_pool
        self.fail = fail
        self.calls = []

    def set_node_pool_size(self, request):
        self.calls.append(("set_node_pool_size", request))
        if self.fail:
            raise self.fail

    def list_operations(self, request):
        self.calls.append(("list_operations", request))
        if self.fail:
            raise self.fail
        return types.SimpleNamespace(operations=self.operations)

    def get_node_pool(self, request):
        self.calls.append(("get_node_pool", request))
        return self.node_pool


def test_gke_set_node_pool_size_shape():
    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    fake = _FakeGKEClient()
    GKEContainerClient(fake).set_node_pool_size(
        "proj", "us-central2-b", "tpu-cluster", "v5e-pool", 4
    )
    assert fake.calls == [
        (
            "set_node_pool_size",
            {
                "name": "projects/proj/locations/us-central2-b"
                "/clusters/tpu-cluster/nodePools/v5e-pool",
                "node_count": 4,
            },
        )
    ]


def test_gke_pending_operations_filters_target_and_status():
    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    pool_link = (
        "https://container.googleapis.com/v1/projects/proj/locations/l"
        "/clusters/c/nodePools/p"
    )
    other_pool = pool_link.replace("nodePools/p", "nodePools/other")
    cluster_link = pool_link.rsplit("/nodePools", 1)[0]
    fake = _FakeGKEClient(
        operations=[
            _FakeOperation("op-resize", "RUNNING", pool_link),
            _FakeOperation("op-done", "DONE", pool_link),
            _FakeOperation("op-other", "RUNNING", other_pool),
            _FakeOperation("op-cluster", "RUNNING", cluster_link),
        ]
    )
    pending = GKEContainerClient(fake).pending_operations(
        "proj", "l", "c", "p"
    )
    # the pool's own op + the cluster-scoped op (GKE's per-cluster
    # operation lock blocks our resize too); done + other-pool excluded
    assert pending == ["op-resize", "op-cluster"]


def test_gke_pending_operations_sibling_prefix_pool_excluded():
    """Suffix matching, not substring: a resize on pool 'v5e-large' must
    not report pool 'v5e' unstable."""
    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    sibling_link = (
        "https://container.googleapis.com/v1/projects/proj/locations/l"
        "/clusters/c/nodePools/v5e-large"
    )
    fake = _FakeGKEClient(
        operations=[_FakeOperation("op-sibling", "RUNNING", sibling_link)]
    )
    assert (
        GKEContainerClient(fake).pending_operations("proj", "l", "c", "v5e")
        == []
    )


def test_gke_retry_error_classified_retryable():
    """google.api_core RetryError subclasses GoogleAPIError only (not
    GoogleAPICallError) and must still be classified retryable."""
    import google.api_core.exceptions as gex

    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    client = GKEContainerClient(
        _FakeGKEClient(fail=gex.RetryError("deadline", cause=None))
    )
    with pytest.raises(RetryableError) as excinfo:
        client.set_node_pool_size("p", "l", "c", "pool", 1)
    assert excinfo.value.retryable
    assert excinfo.value.code == "RetryError"


def test_gke_non_tpu_pool_template_is_none():
    """A pool whose capacity can't be declared (non-TPU machine type)
    yields None — an empty-allocatable template would read as a
    zero-capacity node and block scale-from-zero entirely."""
    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    config = types.SimpleNamespace(
        machine_type="n2-standard-8", labels={"tier": "web"}, taints=[]
    )
    fake = _FakeGKEClient(node_pool=types.SimpleNamespace(config=config))
    assert (
        GKEContainerClient(fake).node_pool_template("p", "l", "c", "pool")
        is None
    )


def test_gke_error_translation_preserves_terminality():
    import google.api_core.exceptions as gex

    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    client = GKEContainerClient(
        _FakeGKEClient(fail=gex.ServiceUnavailable("backend down"))
    )
    with pytest.raises(RetryableError) as excinfo:
        client.set_node_pool_size("p", "l", "c", "pool", 1)
    assert excinfo.value.retryable
    assert excinfo.value.code == "ServiceUnavailable"

    client = GKEContainerClient(
        _FakeGKEClient(fail=gex.PermissionDenied("nope"))
    )
    with pytest.raises(RetryableError) as excinfo:
        client.set_node_pool_size("p", "l", "c", "pool", 1)
    assert not excinfo.value.retryable
    assert excinfo.value.code == "PermissionDenied"


def test_gke_node_pool_template_tpu_machine_type():
    from karpenter_tpu.cloudprovider.gke_sdk import GKEContainerClient

    config = types.SimpleNamespace(
        machine_type="ct5lp-hightpu-4t",
        labels={"pool-tier": "batch"},
        taints=[
            types.SimpleNamespace(
                key="google.com/tpu",
                value="present",
                effect=types.SimpleNamespace(name="NO_SCHEDULE"),
            )
        ],
    )
    fake = _FakeGKEClient(node_pool=types.SimpleNamespace(config=config))
    raw = GKEContainerClient(fake).node_pool_template("p", "l", "c", "pool")
    template = node_template_from_raw(raw)
    assert str(template.allocatable["google.com/tpu"]) == "4"
    assert (
        template.labels["node.kubernetes.io/instance-type"]
        == "ct5lp-hightpu-4t"
    )
    assert template.taints[0].effect == "NoSchedule"


def test_tpu_chips_per_host_parsing():
    from karpenter_tpu.cloudprovider.gke_sdk import _tpu_chips_per_host

    assert _tpu_chips_per_host("ct5lp-hightpu-4t") == 4
    assert _tpu_chips_per_host("ct6e-standard-8t") == 8
    assert _tpu_chips_per_host("n2-standard-8") is None
    assert _tpu_chips_per_host("e2-micro") is None


def test_monitoring_pubsub_latest_point(monkeypatch):
    """MonitoringPubSubClient against a stubbed monitoring_v3 module."""
    from karpenter_tpu.cloudprovider.gke_sdk import MonitoringPubSubClient

    monitoring_mod = types.ModuleType("google.cloud.monitoring_v3")
    monitoring_mod.TimeInterval = lambda d: d
    monitoring_mod.ListTimeSeriesRequest = types.SimpleNamespace(
        TimeSeriesView=types.SimpleNamespace(FULL="FULL")
    )
    monkeypatch.setitem(
        sys.modules, "google.cloud.monitoring_v3", monitoring_mod
    )

    requests = []

    class _Metrics:
        def list_time_series(self, request):
            requests.append(request)
            point = types.SimpleNamespace(
                value=types.SimpleNamespace(int64_value=42)
            )
            return [types.SimpleNamespace(points=[point])]

    client = MonitoringPubSubClient(_Metrics(), clock=lambda: 1000.0)
    assert client.num_undelivered_messages("proj", "work-queue") == 42
    assert "num_undelivered_messages" in requests[0]["filter"]
    assert 'subscription_id = "work-queue"' in requests[0]["filter"]
    assert client.oldest_unacked_message_age_seconds("proj", "wq") == 42
    assert "oldest_unacked_message_age" in requests[1]["filter"]


def test_tpu_factory_binds_gke_sdk_when_available(monkeypatch):
    """TPUFactory auto-binds the container client when container_v1 is
    importable (stubbed here), mirroring the AWS selection rule."""
    from karpenter_tpu.cloudprovider import gke_sdk
    from karpenter_tpu.cloudprovider.tpu import TPUFactory

    container_mod = types.ModuleType("google.cloud.container_v1")
    container_mod.ClusterManagerClient = _FakeGKEClient
    monkeypatch.setitem(
        sys.modules, "google.cloud.container_v1", container_mod
    )
    monkeypatch.setattr(gke_sdk, "container_sdk_available", lambda: True)
    factory = TPUFactory(Options(), sdk_autobind=True)
    assert isinstance(factory.container_api, gke_sdk.GKEContainerClient)
    # direct construction without the flag keeps the guidance stub
    unbound = TPUFactory(Options())
    with pytest.raises(RuntimeError, match="no container API client"):
        unbound.container_api.set_node_pool_size("p", "l", "c", "pool", 1)
