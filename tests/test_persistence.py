"""Durable store: WAL + snapshot checkpoint/resume.

The reference's durability contract (SURVEY.md §5): all durable state lives
in CRD spec/status (etcd); controllers are stateless and resume by
re-listing. These tests assert DurableStore provides the same contract on a
local data dir: every mutation survives a restart byte-exactly (specs,
status incl. conditions and LastScaleTime, identity metadata), compaction
is transparent, and a torn WAL tail (crash mid-append) loses at most the
torn record.
"""

import json
import os
import subprocess
import sys

import pytest

from karpenter_tpu.api import HorizontalAutoscaler, Pod, ScalableNodeGroup
from karpenter_tpu.api.conditions import ACTIVE, TRUE
from karpenter_tpu.api.core import Container, ObjectMeta, PodSpec
from karpenter_tpu.api.horizontalautoscaler import (
    CrossVersionObjectReference,
    HorizontalAutoscalerSpec,
)
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroupSpec
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.store import DurableStore, Scale, Store, open_store
from karpenter_tpu.utils.quantity import Quantity


def sng(name="group", replicas=None):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id=name
        ),
    )


def ha(name="ha"):
    return HorizontalAutoscaler(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="autoscaling.karpenter.sh/v1alpha1",
                kind="ScalableNodeGroup",
                name="group",
            ),
            min_replicas=1,
            max_replicas=10,
        ),
    )


def pod(name, node=None, cpu="100m"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            node_name=node,
            containers=[
                Container(requests={"cpu": Quantity.parse(cpu)})
            ],
        ),
    )


class TestResume:
    def test_crud_survives_restart(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        created = s1.create(sng(replicas=3))
        other = s1.create(sng("other", replicas=1))
        s1.delete("ScalableNodeGroup", "default", "other")
        fresh = s1.get("ScalableNodeGroup", "default", "group")
        fresh.spec.replicas = 7
        s1.update(fresh)
        s1.close()

        s2 = DurableStore(d)
        got = s2.get("ScalableNodeGroup", "default", "group")
        assert got.spec.replicas == 7
        assert got.metadata.uid == created.metadata.uid
        assert got.metadata.creation_timestamp == pytest.approx(
            created.metadata.creation_timestamp
        )
        assert s2.try_get("ScalableNodeGroup", "default", "other") is None
        # resourceVersions keep climbing — a stale pre-restart read must
        # still lose optimistic concurrency after resume
        assert other.metadata.resource_version < s2.create(
            sng("third")
        ).metadata.resource_version

    def test_status_and_conditions_survive(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        obj = s1.create(ha())
        obj.status.current_replicas = 4
        obj.status.desired_replicas = 5
        obj.status.last_scale_time = 1234.5
        obj.status_conditions().mark_true(ACTIVE)
        s1.patch_status(obj)
        s1.close()

        s2 = DurableStore(d)
        got = s2.get("HorizontalAutoscaler", "default", "ha")
        assert got.status.desired_replicas == 5
        assert got.status.last_scale_time == 1234.5  # stabilization memory
        cond = got.status_conditions().get(ACTIVE)
        assert cond is not None and cond.status == TRUE

    def test_pod_index_rebuilt(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        s1.create(pod("a", node="n1"))
        s1.create(pod("b", node="n1", cpu="1500m"))
        s1.create(pod("c", node="n2"))
        s1.close()

        s2 = DurableStore(d)
        names = sorted(p.metadata.name for p in s2.pods_on_node("n1"))
        assert names == ["a", "b"]
        got = {p.metadata.name: p for p in s2.pods_on_node("n1")}
        assert got["b"].spec.containers[0].requests["cpu"] == Quantity.parse(
            "1500m"
        )

    def test_scale_subresource_write_survives(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        s1.create(sng(replicas=2))
        s1.update_scale(
            "ScalableNodeGroup",
            Scale(
                namespace="default",
                name="group",
                spec_replicas=9,
                status_replicas=2,
            ),
        )
        s1.close()
        s2 = DurableStore(d)
        assert s2.get("ScalableNodeGroup", "default", "group").spec.replicas == 9

    def test_lease_survives(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        elector = LeaderElector(s1, identity="me", clock=lambda: 100.0)
        assert elector.try_acquire()
        s1.close()
        s2 = DurableStore(d)
        lease = s2.get("Lease", "kube-system", "karpenter-leader")
        assert lease.holder == "me"


class TestCompaction:
    def test_compaction_transparent(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d, compact_every=5)
        for i in range(12):  # crosses two compaction thresholds
            s1.create(sng(f"g{i}", replicas=i))
        s1.close()
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        s2 = DurableStore(d)
        assert len(s2.list("ScalableNodeGroup")) == 12
        assert s2.get("ScalableNodeGroup", "default", "g7").spec.replicas == 7

    def test_explicit_compact_truncates_wal(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        for i in range(3):
            s1.create(sng(f"g{i}"))
        s1.compact()
        assert os.path.getsize(os.path.join(d, "wal.jsonl")) == 0
        s1.create(sng("after"))
        s1.close()
        s2 = DurableStore(d)
        assert len(s2.list("ScalableNodeGroup")) == 4


class TestCrashTolerance:
    def test_torn_wal_tail_discarded(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        s1.create(sng("good", replicas=1))
        s1.close()
        with open(os.path.join(d, "wal.jsonl"), "a") as f:
            f.write('{"event": "Added", "object": {"kind": "Scal')  # torn
        s2 = DurableStore(d)
        assert s2.get("ScalableNodeGroup", "default", "good").spec.replicas == 1
        # the store keeps working after recovery
        s2.create(sng("next"))
        s2.close()
        s3 = DurableStore(d)
        assert len(s3.list("ScalableNodeGroup")) == 2

    def test_missing_trailing_newline_repaired(self, tmp_path):
        """A crash can persist a full record minus its newline; the next
        session must not concatenate its first append onto that line (which
        a later recovery would discard wholesale as one torn tail)."""
        d = str(tmp_path)
        s1 = DurableStore(d)
        s1.create(sng("a", replicas=1))
        s1.close()
        wal = os.path.join(d, "wal.jsonl")
        with open(wal, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            assert f.read(1) == b"\n"
            f.seek(-1, os.SEEK_END)
            f.truncate()  # simulate the tear at the newline boundary
        s2 = DurableStore(d)
        s2.create(sng("b", replicas=2))
        s2.close()
        s3 = DurableStore(d)
        assert len(s3.list("ScalableNodeGroup")) == 2  # neither lost

    def test_uids_unique_across_restart(self, tmp_path):
        """The uid counter is process-global; a NEW process resuming the
        same data dir must not mint uids already held by recovered objects."""
        d = str(tmp_path)
        script = (
            "from karpenter_tpu.store import DurableStore;"
            "import tests.test_persistence as t;"
            f"s = DurableStore({d!r});"
            "print(s.create(t.sng('a')).metadata.uid);"
            "s.close()"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        other_process_uid = proc.stdout.strip()
        s2 = DurableStore(d)
        assert s2.get("ScalableNodeGroup", "default", "a").metadata.uid == (
            other_process_uid
        )
        fresh_uid = s2.create(sng("b")).metadata.uid
        assert fresh_uid != other_process_uid
        s2.close()

    def test_wal_records_are_rv_ordered(self, tmp_path):
        d = str(tmp_path)
        s1 = DurableStore(d)
        s1.create(sng("a"))
        obj = s1.get("ScalableNodeGroup", "default", "a")
        obj.spec.replicas = 2
        s1.update(obj)
        s1.close()
        with open(os.path.join(d, "wal.jsonl")) as f:
            rvs = [
                json.loads(line)["object"]["metadata"]["resourceVersion"]
                for line in f
                if line.strip()
            ]
        assert rvs == sorted(rvs) and len(rvs) == 2


class TestDataDirLock:
    def test_second_process_rejected(self, tmp_path):
        """Two processes on one --data-dir would interleave WAL appends;
        fail fast like etcd on a locked member dir."""
        d = str(tmp_path)
        s1 = DurableStore(d)
        with pytest.raises(RuntimeError, match="locked"):
            DurableStore(d)
        s1.close()
        s2 = DurableStore(d)  # released on close
        s2.close()


class TestAppendFailure:
    class _BrokenFile:
        closed = False

        def write(self, *_):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def close(self):
            self.closed = True

        def fileno(self):
            raise OSError(9, "Bad file descriptor")

    def test_memory_stays_authoritative_and_journal_self_heals(self, tmp_path):
        d = str(tmp_path)
        s = DurableStore(d)
        events = []
        s.watch(None, lambda ev, obj: events.append(obj.metadata.name))
        s.create(sng("a"))
        real_wal = s._wal_file
        s._wal_file = self._BrokenFile()  # disk "fills"
        s.create(sng("b"))  # must NOT raise; watchers must still fire
        assert events == ["a", "b"]
        assert s._wal_dirty
        real_wal.close()
        s.create(sng("c"))  # first success -> full snapshot heals the gap
        assert not s._wal_dirty
        s.close()
        s2 = DurableStore(d)
        names = sorted(o.metadata.name for o in s2.list("ScalableNodeGroup"))
        assert names == ["a", "b", "c"]  # nothing acknowledged was lost
        s2.close()


class TestFactory:
    def test_open_store_dispatch(self, tmp_path):
        durable = open_store(str(tmp_path))
        assert isinstance(durable, DurableStore)
        durable.close()
        plain = open_store(None)
        assert isinstance(plain, Store) and not isinstance(plain, DurableStore)


class TestRestorabilityGate:
    def test_unregistered_kind_fails_at_write_not_recovery(self, tmp_path):
        """Journaling an unregistered custom kind must fail AT CREATE
        (actionable, points at register_persistent_kind) instead of
        succeeding and crashing the next process start inside
        _recover — the duck-typed scale path makes such objects easy
        to make."""
        from dataclasses import dataclass, field

        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.store.persistence import (
            DurableStore,
            register_persistent_kind,
        )

        @dataclass
        class _WidgetSpec:
            replicas: int = 1

        @dataclass
        class _WidgetStatus:
            replicas: int = 0

        @dataclass
        class _Widget:
            metadata: ObjectMeta = field(default_factory=ObjectMeta)
            spec: _WidgetSpec = field(default_factory=_WidgetSpec)
            status: _WidgetStatus = field(default_factory=_WidgetStatus)
            KIND = "FuzzWidget"

        from karpenter_tpu.store import persistence as _p
        from karpenter_tpu.store.store import ADDED

        try:
            store = DurableStore(str(tmp_path / "data"))
            try:
                with pytest.raises(
                    ValueError, match="register_persistent_kind"
                ):
                    store.create(_Widget(metadata=ObjectMeta(name="w")))
                # the watch-driven entry path is gated too
                with pytest.raises(
                    ValueError, match="register_persistent_kind"
                ):
                    store.apply_event(
                        ADDED, _Widget(metadata=ObjectMeta(name="w2"))
                    )
                # registration makes the SAME object durable end to end
                register_persistent_kind("FuzzWidget", _Widget)
                store.create(_Widget(metadata=ObjectMeta(name="w")))
            finally:
                store.close()
            reopened = DurableStore(str(tmp_path / "data"))
            try:
                assert (
                    reopened.get(
                        "FuzzWidget", "default", "w"
                    ).spec.replicas
                    == 1
                )
            finally:
                reopened.close()
        finally:
            # always unregister: a leak would warp later unregistered-kind
            # assertions in this process
            _p._EXTRA_KINDS.pop("FuzzWidget", None)
