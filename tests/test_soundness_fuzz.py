"""Randomized soundness fuzz: the pending-pods signal must NEVER
promise a placement the kube-scheduler refuses.

Random fleets (zones x racks, bound pods, workloads mixing hard spread,
self/foreign (anti-)affinity, namespaceSelector scopes) are solved
through the real encode+solve (the simulate() surface, which shares the
production path), and every promised placement is checked against
SCALAR final-state rules:

- hard spread (selfMatch): in the final state (existing + promised),
  no eligible domain exceeds the global minimum over filter-passing
  domains by more than maxSkew — any legal placement sequence ends
  within that bound, so violating it proves an impossible promise;
- self anti-affinity: at most one matching pod (existing + promised)
  per domain of every constrained key;
- self co-affinity: a promised pod's domain holds an existing matching
  pod, or no matching pod exists anywhere (the bootstrap) and ALL the
  workload's promised pods share one domain;
- foreign anti: the promised pod's domain holds no existing pod
  matching the term's selector in its namespace scope;
- foreign co: the domain holds one (no bootstrap);
- accounting: promised + unschedulable == pending.

Under-promising (extra unschedulable) is allowed — the documented
conservative direction; over-promising fails the fuzz.
"""

import os

import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Affinity,
    Container,
    LabelSelector,
    Namespace,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PodStatus,
    TopologySpreadConstraint,
    resource_list,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.simulate import simulate
from karpenter_tpu.store.store import Store

ZONE = "topology.kubernetes.io/zone"
RACK = "x-example.com/rack"
APPS = ("red", "blue", "green")


def build_fleet(rng):
    """(store, groups: {name: labels}) — a random constrained fleet."""
    store = Store()
    n_zones = int(rng.integers(2, 4))
    n_groups = int(rng.integers(2, 5))
    groups = {}
    for g in range(n_groups):
        labels = {
            "group": f"g{g}",
            ZONE: f"z{int(rng.integers(0, n_zones))}",
            RACK: f"r{int(rng.integers(0, 2))}",
        }
        groups[f"group-{g}"] = labels
        store.create(
            Node(
                metadata=ObjectMeta(name=f"n{g}", labels=dict(labels)),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable=resource_list(
                        cpu="64", memory="64Gi", pods="110"
                    ),
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
        store.create(
            MetricsProducer(
                metadata=ObjectMeta(name=f"group-{g}"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"group": f"g{g}"}
                    )
                ),
            )
        )
    # sometimes an unmanaged node (outside-minimum / foreign domains)
    if rng.random() < 0.5:
        store.create(
            Node(
                metadata=ObjectMeta(
                    name="unmanaged",
                    labels={ZONE: f"z{int(rng.integers(0, n_zones))}"},
                ),
                spec=NodeSpec(),
                status=NodeStatus(
                    conditions=[NodeCondition("Ready", "True")]
                ),
            )
        )
    # namespaces (sometimes absent: the fallback path)
    if rng.random() < 0.7:
        for team in ("a", "b"):
            store.create(
                Namespace(
                    metadata=ObjectMeta(
                        name=f"team-{team}",
                        namespace="",
                        labels={"team": team},
                    )
                )
            )
    # bound pods: random apps on random group nodes, random namespaces;
    # some carry a tier label so the same-key different-selector spread
    # dimension sees imbalanced tier counts
    for i in range(int(rng.integers(0, 12))):
        app = APPS[int(rng.integers(0, len(APPS)))]
        labels = {"app": app}
        if rng.random() < 0.4:
            labels["tier"] = f"t{int(rng.integers(0, 2))}"
        store.create(
            Pod(
                metadata=ObjectMeta(
                    name=f"bound-{i}",
                    namespace=rng.choice(
                        ["default", "team-a", "team-b"]
                    ),
                    labels=labels,
                ),
                spec=PodSpec(
                    node_name=f"n{int(rng.integers(0, n_groups))}",
                    containers=[
                        Container(requests=resource_list(cpu="1"))
                    ],
                ),
                status=PodStatus(phase="Running"),
            )
        )
    return store, groups


def random_workload(rng, widx, tier_skew=None):
    """(pods, spec dict describing the constraints for the validator).

    tier_skew (run-level, from _run_seed): when set, WORKLOAD 0 carries
    a SECOND zone DoNotSchedule constraint selecting the shared tier
    label — the same-topology-key different-selector class whose skew
    must bind against the tier's own census counts (r3 advisor, medium
    — fixed r4; bound pods with tier labels supply the imbalance).
    Only ONE workload is constrained: tier-matching PENDING pods of
    other workloads are a pending-vs-pending interaction the solver
    documents as out of scope (each workload's shape has its own
    ledgers), and the oracle orders the constrained workload first, so
    its bound counts only bound pods plus its own adds.
    """
    app = f"w{widx}"
    count = int(rng.integers(1, 6))
    # a tier label SHARED across workloads (two tiers)
    tier = f"t{widx % 2}"
    tier_skew = tier_skew if widx == 0 else None
    spec = {
        "app": app,
        "tier": tier,
        "spread": None,
        "min_domains": None,
        "rack_spread": None,
        "tier_spread": tier_skew,
        "self_anti": False,
        "self_anti_rack": False,
        "self_co": False,
        "self_co_hostname": False,
        "self_co_extra_ns": None,
        "foreign": [],
    }
    constraints = []
    anti_terms = []
    co_terms = []
    if tier_skew is not None:
        constraints.append(
            TopologySpreadConstraint(
                max_skew=tier_skew,
                topology_key=ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector={"matchLabels": {"tier": tier}},
            )
        )
    if rng.random() < 0.6:
        skew = int(rng.integers(1, 3))
        spec["spread"] = skew
        min_domains = (
            int(rng.integers(2, 5)) if rng.random() < 0.3 else None
        )
        spec["min_domains"] = min_domains
        constraints.append(
            TopologySpreadConstraint(
                max_skew=skew,
                topology_key=ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector={"matchLabels": {"app": app}},
                min_domains=min_domains,
            )
        )
        if rng.random() < 0.3:
            rack_skew = int(rng.integers(1, 3))
            spec["rack_spread"] = rack_skew
            constraints.append(
                TopologySpreadConstraint(
                    max_skew=rack_skew,
                    topology_key=RACK,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": app}},
                )
            )
    if rng.random() < 0.2:
        # soft constraints never constrain, so no validator rule — but
        # mixed nil/set selector forms crashed the whole solve before
        # _total_order (r3 advisor, high; fixed r4)
        constraints.append(
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=RACK,
                when_unsatisfiable="ScheduleAnyway",
                label_selector=(
                    None
                    if rng.random() < 0.5
                    else {"matchLabels": {"app": app}}
                ),
            )
        )
    if rng.random() < 0.4:
        spec["self_anti"] = True
        anti_terms.append(
            PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": app}),
                topology_key=ZONE,
            )
        )
        if rng.random() < 0.3:
            spec["self_anti_rack"] = True
            anti_terms.append(
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"app": app}
                    ),
                    topology_key=RACK,
                )
            )
        if rng.random() < 0.15:
            # anti + hostname co TOGETHER: contradictory beyond one
            # replica — the hand-out must truncate to one promise
            # total (reachable combination, r4 code review)
            spec["self_co_hostname"] = True
            co_terms.append(
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"app": app}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            )
    elif rng.random() < 0.15:
        # hostname self co-location: all replicas on ONE node — with a
        # matching scheduled pod it pins to that existing node
        # (unschedulable on scale-up); empty census bootstraps exactly
        # one promised replica (r4 conservative modeling)
        spec["self_co_hostname"] = True
        co_terms.append(
            PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": app}),
                topology_key="kubernetes.io/hostname",
            )
        )
    elif rng.random() < 0.3:
        spec["self_co"] = True
        term = PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=ZONE,
        )
        if rng.random() < 0.4:
            # the term reaches an EXTRA namespace: matching pods THERE
            # pin the scheduler even when the own namespace is empty
            # (r3 advisor, low — fixed r4 with the sign +2 projection)
            spec["self_co_extra_ns"] = "team-a"
            term.namespaces = ["default", "team-a"]
        co_terms.append(term)
    if rng.random() < 0.5:
        target = APPS[int(rng.integers(0, len(APPS)))]
        sign = "anti" if rng.random() < 0.6 else "co"
        term = PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": target}),
            topology_key=ZONE,
        )
        scope = ["default"]
        if rng.random() < 0.4:
            term.namespace_selector = LabelSelector(
                match_labels={"team": "a"}
            )
            scope = ("~selector", "a")
        spec["foreign"].append((sign, target, scope))
        (anti_terms if sign == "anti" else co_terms).append(term)
    affinity = None
    if anti_terms or co_terms:
        affinity = Affinity(
            pod_anti_affinity=(
                PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=anti_terms
                )
                if anti_terms
                else None
            ),
            pod_affinity=(
                PodAffinity(
                    required_during_scheduling_ignored_during_execution=co_terms
                )
                if co_terms
                else None
            ),
        )
    pods = []
    for i in range(count):
        pods.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"{app}-{i}",
                    labels={"app": app, "tier": tier},
                ),
                spec=PodSpec(
                    node_name="",
                    containers=[
                        Container(
                            requests=resource_list(
                                cpu="1", memory="1Gi"
                            )
                        )
                    ],
                    affinity=affinity,
                    topology_spread_constraints=constraints,
                ),
            )
        )
    return pods, spec


def bound_index(store):
    """{(namespace, app): [(zone, rack)]} of bound non-terminal pods."""
    labels_by_node = {
        n.metadata.name: n.metadata.labels for n in store.list("Node")
    }
    out = {}
    for pod in store.list("Pod"):
        if not pod.spec.node_name or pod.status.phase in (
            "Succeeded",
            "Failed",
        ):
            continue
        node_labels = labels_by_node.get(pod.spec.node_name, {})
        zone = node_labels.get(ZONE)
        if zone is None:
            continue
        key = (
            pod.metadata.namespace,
            pod.metadata.labels.get("app"),
        )
        out.setdefault(key, []).append((zone, node_labels.get(RACK)))
    return out


def scopes_zones(store, bound, target, scope):
    """Zones occupied by pods of `target` app within a namespace scope."""
    if isinstance(scope, tuple) and scope[0] == "~selector":
        team = scope[1]
        names = {
            ns.metadata.name
            for ns in store.list("Namespace")
            if ns.metadata.labels.get("team") == team
        }
        if not store.list("Namespace"):
            names = set()
        zones = set()
        for (ns, app), pairs in bound.items():
            if app == target and ns in names:
                zones.update(z for z, _ in pairs)
        return zones, bool(names) or bool(store.list("Namespace"))
    zones = set()
    for ns in scope:
        zones.update(
            z
            for (n, app), pairs in bound.items()
            if n == ns and app == target
            for z, _ in pairs
        )
    return zones, True


def _validate_tier_spread(store, workloads, promised, present_zones,
                          rng_label):
    """SAME topology key, DIFFERENT selector: workload 0's tier
    constraint binds against the TIER's own census counts (bound pods
    with tier labels supply the imbalance the app selector doesn't
    see). Sound rule under a w0-first placement order: counts = bound
    tier-matching pods + w0's own adds (other workloads' pending
    tier-carrying pods may be placed later and are not counted); for
    any zone that received a w0 add, the last add there required
    count - running_min <= skew with the running min only growing, so
    final[z] - final_min <= skew. Zones holding only pre-existing
    excess are unconstrained (legal initial imbalance)."""
    spec0 = workloads[0]
    skew = spec0["tier_spread"]
    if not skew:
        return
    tier = spec0["tier"]
    node_zone = {
        n.metadata.name: n.metadata.labels.get(ZONE)
        for n in store.list("Node")
    }
    final = {z: 0 for z in present_zones}
    for pod in store.list("Pod"):
        if (
            pod.spec.node_name
            and pod.status.phase not in ("Succeeded", "Failed")
            and pod.metadata.namespace == "default"
            and pod.metadata.labels.get("tier") == tier
        ):
            zone = node_zone.get(pod.spec.node_name)
            if zone in final:
                final[zone] += 1
    added = set()
    for z, _ in promised.get(spec0["app"], []):
        final[z] += 1
        added.add(z)
    if not added:
        return
    floor = min(final.values())
    for zone in added:
        assert final[zone] - floor <= skew, (
            f"[{rng_label}] tier {tier}: promised zone {zone} at "
            f"{final[zone]} exceeds min {floor} + skew {skew}; "
            f"final={final}"
        )


def validate(store, groups, workloads, report, rng_label):  # lint: allow-complexity — one block per scheduler rule, the whole scalar oracle in one place
    """Assert every promised placement admissible; returns promised count."""
    bound = bound_index(store)
    group_zone = {name: labels.get(ZONE) for name, labels in groups.items()}
    group_rack = {name: labels.get(RACK) for name, labels in groups.items()}
    # per-workload promised (zone, rack) multiset from per-row detail
    promised = {}
    for row in report["rows"]:
        if row["assigned"] is None:
            continue
        pod_name = row["pod"].split("/", 1)[1]
        app = pod_name.rsplit("-", 1)[0]
        gname = row["assigned"].split("/", 1)[1]
        promised.setdefault(app, []).extend(
            [(group_zone[gname], group_rack[gname])] * row["pods"]
        )
    # domains of ALL live nodes (incl. unmanaged): the spread filter
    # set for pods with no nodeSelector
    present_zones = {
        n.metadata.labels.get(ZONE)
        for n in store.list("Node")
        if ZONE in n.metadata.labels
    }
    present_racks = {
        n.metadata.labels.get(RACK)
        for n in store.list("Node")
        if RACK in n.metadata.labels
    }
    _validate_tier_spread(
        store, workloads, promised, present_zones, rng_label
    )
    for spec in workloads:
        app = spec["app"]
        placed_pairs = promised.get(app, [])
        placed = [z for z, _ in placed_pairs]
        bound_pairs = bound.get(("default", app), [])
        if spec["spread"] is not None and placed:
            skew = spec["spread"]
            final = {z: 0 for z in present_zones}
            for z, _ in bound_pairs:
                if z in final:
                    final[z] += 1
            for z in placed:
                final[z] += 1
            floor = min(final.values())
            worst = max(final.values())
            assert worst - floor <= skew, (
                f"[{rng_label}] {app}: spread skew {worst - floor} > "
                f"{skew}; final={final}, placed={placed}"
            )
            min_domains = spec["min_domains"]
            if min_domains and len(present_zones) < min_domains:
                # the scheduler's global-minimum-0 rule
                assert worst <= skew, (
                    f"[{rng_label}] {app}: minDomains cap {skew} "
                    f"exceeded: final={final}"
                )
        if spec["rack_spread"] is not None and placed_pairs:
            skew = spec["rack_spread"]
            final = {r: 0 for r in present_racks}
            for _, rack in bound_pairs:
                if rack in final:
                    final[rack] += 1
            for _, rack in placed_pairs:
                final[rack] += 1
            floor = min(final.values())
            worst = max(final.values())
            assert worst - floor <= skew, (
                f"[{rng_label}] {app}: rack skew {worst - floor} > "
                f"{skew}; final={final}"
            )
        if spec["self_anti"] and placed:
            bound_zones = [z for z, _ in bound_pairs]
            for zone in set(placed):
                total = placed.count(zone) + bound_zones.count(zone)
                assert total <= 1, (
                    f"[{rng_label}] {app}: {total} replicas in {zone} "
                    f"violate self anti-affinity"
                )
        if spec["self_anti_rack"] and placed_pairs:
            racks = [r for _, r in placed_pairs] + [
                r for _, r in bound_pairs if r is not None
            ]
            for rack in set(r for _, r in placed_pairs):
                assert racks.count(rack) <= 1, (
                    f"[{rng_label}] {app}: {racks.count(rack)} replicas "
                    f"in rack {rack} violate self anti-affinity"
                )
        if spec["self_co"] and placed:
            existing = set(z for z, _ in bound_pairs)
            if spec["self_co_extra_ns"]:
                # the term's namespaces list reaches a second
                # namespace: matching pods THERE pin placement too
                # (r3 advisor, low — fixed r4)
                existing |= {
                    z
                    for z, _ in bound.get(
                        (spec["self_co_extra_ns"], app), []
                    )
                }
            if existing:
                assert set(placed) <= existing, (
                    f"[{rng_label}] {app}: co replicas outside "
                    f"occupied zones {existing}: {placed}"
                )
            else:
                assert len(set(placed)) == 1, (
                    f"[{rng_label}] {app}: bootstrap co split across "
                    f"{set(placed)}"
                )
        if spec["self_co_hostname"]:
            # one-node co-residence: at most ONE promised replica, and
            # none at all when a matching scheduled pod already pins
            # the workload to its existing node
            assert len(placed) <= 1, (
                f"[{rng_label}] {app}: {len(placed)} replicas promised "
                f"under hostname self co-location"
            )
            if bound_pairs:
                assert not placed, (
                    f"[{rng_label}] {app}: promised {placed} despite a "
                    f"scheduled matching pod pinning the node"
                )
        for sign, target, scope in spec["foreign"]:
            occupied, judgeable = scopes_zones(
                store, bound, target, scope
            )
            for zone in placed:
                if sign == "anti" and judgeable:
                    assert zone not in occupied, (
                        f"[{rng_label}] {app}: placed in {zone} beside "
                        f"{target} (foreign anti)"
                    )
                if sign == "co":
                    assert zone in occupied, (
                        f"[{rng_label}] {app}: placed in {zone} but "
                        f"{target} occupies only {occupied}"
                    )
    return sum(len(v) for v in promised.values())


def _run_seed(seed, max_workloads=3):
    rng = np.random.default_rng(seed)
    store, groups = build_fleet(rng)
    n_groups = len(groups)
    workloads = []
    pending_total = 0
    # run-level same-key different-selector dimension (one shared skew
    # keeps the tier oracle sound — random_workload docstring)
    tier_skew = int(rng.integers(1, 3)) if rng.random() < 0.25 else None
    for widx in range(int(rng.integers(1, max_workloads + 1))):
        pods, spec = random_workload(rng, widx, tier_skew=tier_skew)
        workloads.append(spec)
        pending_total += len(pods)
        for pod in pods:
            store.create(pod)
        if spec["self_co_extra_ns"] and rng.random() < 0.6:
            # a TWIN of this workload already runs in the extra
            # namespace: the scheduler pins the co term to its domain
            # even though the own namespace is empty (r4 low fix)
            store.create(
                Pod(
                    metadata=ObjectMeta(
                        name=f"{spec['app']}-twin",
                        namespace=spec["self_co_extra_ns"],
                        labels={"app": spec["app"]},
                    ),
                    spec=PodSpec(
                        node_name=f"n{int(rng.integers(0, n_groups))}",
                        containers=[
                            Container(requests=resource_list(cpu="1"))
                        ],
                    ),
                    status=PodStatus(phase="Running"),
                )
            )
        if rng.random() < 0.3:
            # the workload already RUNS one replica somewhere: the own
            # workload's census paths (co pinning, anti-spent domains,
            # spread self counts) engage, not just the bootstrap
            store.create(
                Pod(
                    metadata=ObjectMeta(
                        name=f"{spec['app']}-live",
                        labels={"app": spec["app"],
                                "tier": spec["tier"]},
                    ),
                    spec=PodSpec(
                        node_name=f"n{int(rng.integers(0, n_groups))}",
                        containers=[
                            Container(requests=resource_list(cpu="1"))
                        ],
                    ),
                    status=PodStatus(phase="Running"),
                )
            )
    report = simulate(store)
    promised = validate(store, groups, workloads, report, seed)
    assert promised + report["unschedulable_pods"] == pending_total, (
        f"seed={seed}: promised {promised} + unschedulable "
        f"{report['unschedulable_pods']} != pending {pending_total}"
    )


class TestSoundnessFuzz:
    @pytest.mark.parametrize("seed", range(60))
    def test_promises_are_scheduler_admissible(self, seed):
        _run_seed(seed)

    @pytest.mark.skipif(
        not os.environ.get("KARPENTER_SCALE_TESTS"),
        reason="wide sweep; battletest sets KARPENTER_SCALE_TESTS=1",
    )
    @pytest.mark.parametrize("seed", range(3000, 3300))
    def test_heavy_fleet_sweep(self, seed):
        """battletest tier: 300 extra seeds with up to 6 workloads per
        solve — the cross-workload interaction surface (shared foreign
        targets, competing budgets) at higher density."""
        _run_seed(seed, max_workloads=6)
