"""Bin-pack solver tests: feasibility masks, assignment, packing quality.

Three tiers (SURVEY.md §4 "solver correctness needs a new tier"):
- exact: device shelf-BFD == NumPy oracle of the same algorithm
- sandwich: LP lower bound <= result, and result close to full-precision FFD
- semantics: taints/tolerations, nodeSelector, resource fit, assignment rules
"""

import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.ops import binpack as B


def make_inputs(
    pod_requests,
    group_allocatable,
    pod_valid=None,
    pod_intolerant=None,
    pod_required=None,
    group_taints=None,
    group_labels=None,
    n_taints=4,
    n_labels=4,
):
    req = np.asarray(pod_requests, np.float32)
    alloc = np.asarray(group_allocatable, np.float32)
    p, t = req.shape[0], alloc.shape[0]
    default = lambda arr, shape: (
        np.asarray(arr, bool) if arr is not None else np.zeros(shape, bool)
    )
    return B.BinPackInputs(
        pod_requests=jnp.asarray(req),
        pod_valid=jnp.asarray(
            np.ones(p, bool) if pod_valid is None else np.asarray(pod_valid, bool)
        ),
        pod_intolerant=jnp.asarray(default(pod_intolerant, (p, n_taints))),
        pod_required=jnp.asarray(default(pod_required, (p, n_labels))),
        group_allocatable=jnp.asarray(alloc),
        group_taints=jnp.asarray(default(group_taints, (t, n_taints))),
        group_labels=jnp.asarray(default(group_labels, (t, n_labels))),
    )


class TestFeasibilityAndAssignment:
    def test_resource_fit(self):
        # pod 0 fits both groups; pod 1 only the big group; pod 2 neither
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1], [3, 1], [9, 9]],
                group_allocatable=[[2, 2], [4, 4]],
            )
        )
        assert out.assigned.tolist() == [0, 1, -1]
        assert int(out.unschedulable) == 1
        assert out.assigned_count.tolist() == [1, 1]

    def test_first_feasible_group_wins(self):
        """DESIGN.md: only a single node group scales up per pod."""
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1]], group_allocatable=[[4, 4], [4, 4]]
            )
        )
        assert out.assigned.tolist() == [0]
        assert out.assigned_count.tolist() == [1, 0]
        assert out.nodes_needed.tolist()[1] == 0

    def test_taints_block_intolerant_pods(self):
        # group 0 carries taint 0; pod 0 doesn't tolerate it, pod 1 does
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1], [1, 1]],
                group_allocatable=[[4, 4], [4, 4]],
                group_taints=[[True, False, False, False], [False] * 4],
                pod_intolerant=[
                    [True, False, False, False],
                    [False, False, False, False],
                ],
            )
        )
        assert out.assigned.tolist() == [1, 0]

    def test_node_selector_requires_group_label(self):
        # pod 0 requires label 2, only group 1 has it
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1]],
                group_allocatable=[[4, 4], [4, 4]],
                group_labels=[[False] * 4, [False, False, True, False]],
                pod_required=[[False, False, True, False]],
            )
        )
        assert out.assigned.tolist() == [1]

    def test_invalid_pods_ignored(self):
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1], [1, 1]],
                group_allocatable=[[4, 4]],
                pod_valid=[True, False],
            )
        )
        assert out.assigned_count.tolist() == [1]
        assert int(out.unschedulable) == 0  # padding rows don't count

    def test_empty_group_infeasible(self):
        out = B.binpack(
            make_inputs(pod_requests=[[1, 1]], group_allocatable=[[0, 0]])
        )
        assert out.assigned.tolist() == [-1]
        assert int(out.unschedulable) == 1


class TestPackingCounts:
    def test_simple_counts(self):
        # 6 pods of half a node each -> 3 nodes
        out = B.binpack(
            make_inputs(
                pod_requests=[[2, 2]] * 6, group_allocatable=[[4, 4]]
            )
        )
        assert out.nodes_needed.tolist() == [3]
        assert out.lp_bound.tolist() == [3]

    def test_whole_node_pods(self):
        out = B.binpack(
            make_inputs(pod_requests=[[4, 4]] * 5, group_allocatable=[[4, 4]])
        )
        assert out.nodes_needed.tolist() == [5]

    def test_mixed_sizes_shelf_packing(self):
        # two 3/4 pods + two 1/4 pods: 2 nodes (3/4+1/4 each)
        out = B.binpack(
            make_inputs(
                pod_requests=[[3, 1], [3, 1], [1, 1], [1, 1]],
                group_allocatable=[[4, 4]],
            )
        )
        assert out.nodes_needed.tolist() == [2]

    def test_dominant_resource_drives_size(self):
        # memory-dominant pod: cpu would allow 4/node but memory only 1/node
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 4]] * 3, group_allocatable=[[4, 4]]
            )
        )
        assert out.nodes_needed.tolist() == [3]

    def test_zero_pending_pods(self):
        out = B.binpack(
            make_inputs(
                pod_requests=[[1, 1]],
                group_allocatable=[[4, 4]],
                pod_valid=[False],
            )
        )
        assert out.nodes_needed.tolist() == [0]
        assert out.lp_bound.tolist() == [0]


class TestOracleExactness:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_kernel_matches_numpy_oracle(self, seed):
        rng = np.random.default_rng(seed)
        t, buckets = 7, 32
        histogram = rng.integers(0, 40, (t, buckets)).astype(np.int32)
        got = np.asarray(B._shelf_bfd(jnp.asarray(histogram), buckets))
        want = B.oracle_shelf_bfd(histogram, buckets)
        np.testing.assert_array_equal(got, want)

    def test_full_pipeline_against_oracle(self):
        rng = np.random.default_rng(7)
        p, t = 300, 5
        req = rng.uniform(0.1, 3.9, (p, 2)).astype(np.float32)
        alloc = np.asarray([[4, 4], [8, 8], [2, 4], [16, 8], [4, 16]], np.float32)
        out = B.binpack(make_inputs(req, alloc))

        # recompute membership + histogram on host, then oracle-pack
        feasible = np.all(req[:, None, :] <= alloc[None, :, :], axis=2)
        assigned = np.where(feasible.any(1), feasible.argmax(1), -1)
        buckets = B.DEFAULT_BUCKETS
        histogram = np.zeros((t, buckets), np.int32)
        for pi in range(p):
            ti = assigned[pi]
            if ti < 0:
                continue
            share = max(req[pi] / alloc[ti])
            b = min(buckets, max(1, int(np.ceil(share * buckets - 1e-6))))
            histogram[ti, b - 1] += 1
        np.testing.assert_array_equal(
            np.asarray(out.assigned), assigned.astype(np.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(out.nodes_needed), B.oracle_shelf_bfd(histogram, buckets)
        )


class TestPackingQuality:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_lp_sandwich_and_ffd_proximity(self, seed):
        rng = np.random.default_rng(seed)
        p = 500
        sizes = rng.uniform(0.05, 1.0, p).astype(np.float32)
        req = np.stack([sizes * 4, sizes * 4], axis=1)
        out = B.binpack(make_inputs(req, [[4, 4]]))
        nodes = int(out.nodes_needed[0])
        lp = int(out.lp_bound[0])
        ffd = B.oracle_ffd(sizes)
        assert lp <= nodes
        # quantization (1/32 ceil) + shelf placement keep us near true FFD
        assert nodes <= ffd * 1.15 + 2, (nodes, ffd, lp)

    # size distributions spanning the regimes that stress bucketized
    # packing differently: quantization inflation (small), near-full nodes
    # (large), shelf reuse (bimodal/harmonic)
    DISTRIBUTIONS = {
        "uniform": lambda rng, p: rng.uniform(0.02, 1.0, p),
        "small": lambda rng, p: rng.uniform(0.01, 0.12, p),
        "large": lambda rng, p: rng.uniform(0.45, 0.95, p),
        "bimodal": lambda rng, p: np.where(
            rng.random(p) < 0.5,
            rng.uniform(0.05, 0.15, p),
            rng.uniform(0.55, 0.8, p),
        ),
        "harmonic": lambda rng, p: 1.0 / rng.integers(1, 20, p),
    }
    # empirical ratchet: grand-total nodes over every (distribution,
    # buckets, seed) case below, measured at the time this test was
    # written. 1% headroom absorbs float-rounding drift across jax
    # versions; a systematic packing-quality regression trips it.
    RATCHET_TOTAL = 22221

    def _fleet_cases(self):
        for buckets in (8, 16, 32, 64):
            for name, gen in self.DISTRIBUTIONS.items():
                for seed in range(6):
                    rng = np.random.default_rng(seed)
                    yield buckets, name, gen(rng, 400).astype(np.float32)

    def test_quality_bounds_over_randomized_fleets(self):
        """Pins the bucketized shelf-BFD's packing quality three ways:

        1. ANALYTIC soundness per fleet: lp <= nodes <= 2*ffd + 2*ceil(P/B)
           + 1. Derivation: any-fit packings never leave two bins that
           would fit together, so nodes <= 2*sum(q) + 1; quantizing up
           adds < 1/B per item, sum(q) <= sum(s) + P/B; and
           ffd >= sum(s). Never flaky, catches catastrophic regressions
           (e.g. one-item-per-bin placement).
        2. FIDELITY per fleet: nodes <= FFD run on the SAME quantized
           sizes — the device shelf algorithm (best-fit by remaining
           capacity) must never pack worse than canonical first-fit-
           decreasing at equal granularity. Holds with equality-or-better
           on every case today.
        3. RATCHET in aggregate: total nodes across all cases within 1%
           of the recorded measurement, so a broad quality drift fails CI
           even if each fleet stays under the loose analytic bound.
        """
        total = 0
        for buckets, name, sizes in self._fleet_cases():
            p = len(sizes)
            req = np.stack([sizes * 4, sizes * 4], axis=1)
            out = B.binpack(make_inputs(req, [[4, 4]]), buckets=buckets)
            nodes = int(out.nodes_needed[0])
            lp = int(out.lp_bound[0])
            ffd = B.oracle_ffd(sizes)
            label = (name, buckets, nodes, ffd, lp)
            assert lp <= nodes, label
            assert nodes <= 2 * ffd + 2 * int(np.ceil(p / buckets)) + 1, label
            quantized = (
                np.clip(
                    np.ceil(sizes.astype(np.float64) * buckets - 1e-6),
                    1,
                    buckets,
                )
                / buckets
            )
            assert nodes <= B.oracle_ffd(quantized), label
            total += nodes
        assert total <= int(self.RATCHET_TOTAL * 1.01), total

    def test_result_is_sufficient_capacity(self):
        """The count must be a VALID packing bound: verify by re-packing the
        true sizes into that many nodes greedily."""
        rng = np.random.default_rng(11)
        sizes = rng.uniform(0.05, 0.95, 200).astype(np.float32)
        req = np.stack([sizes * 4, sizes * 4], axis=1)
        out = B.binpack(make_inputs(req, [[4, 4]]))
        nodes = int(out.nodes_needed[0])
        bins = [1.0] * nodes
        for s in sorted(sizes, reverse=True):
            for i in range(len(bins)):
                if s <= bins[i] + 1e-6:
                    bins[i] -= s
                    break
            else:
                pytest.fail(f"{nodes} nodes insufficient for true sizes")


class TestDeviceResidencyCache:
    """solve() caches the device_put of the last inputs OBJECT (identity-
    keyed): a repeated tick over an unchanged fleet skips the host->device
    transfer. Fresh objects must always recompute."""

    def test_identity_hit_returns_equal_outputs(self):
        # backend="xla" explicitly: auto routes to numpy on the CPU test
        # mesh, which never touches the residency cache under test
        rng = np.random.default_rng(3)
        req = rng.uniform(0.1, 2.0, (40, 2)).astype(np.float32)
        inputs = make_inputs(req, [[4, 4], [8, 8]])
        first = B.solve(inputs, backend="xla")
        again = B.solve(inputs, backend="xla")  # identity hit: cached device arrays
        np.testing.assert_array_equal(
            np.asarray(first.assigned), np.asarray(again.assigned)
        )
        np.testing.assert_array_equal(
            np.asarray(first.nodes_needed), np.asarray(again.nodes_needed)
        )

    def test_fresh_object_recomputes(self):
        req = np.full((10, 2), 0.5, np.float32)
        small = make_inputs(req, [[1, 1]])
        out_small = B.solve(small, backend="xla")
        big = make_inputs(req, [[8, 8]])
        out_big = B.solve(big, backend="xla")  # different object: must not reuse cache
        assert int(out_small.nodes_needed[0]) > int(out_big.nodes_needed[0])


class TestWeightedDedup:
    """pod_weight semantics: solving W duplicate rows as one row with
    weight W must produce identical aggregates — the exactness claim the
    encoder's shape-dedup (_dedup_rows) rests on."""

    def _random_dup_inputs(self, rng, shapes=12, dup_max=40, types=6):
        base_req = rng.uniform(0.05, 4.0, (shapes, 2)).astype(np.float32)
        counts = rng.integers(1, dup_max, shapes)
        full_req = np.repeat(base_req, counts, axis=0)
        alloc = rng.uniform(4.0, 16.0, (types, 2)).astype(np.float32)
        intol_base = rng.random((shapes, 4)) < 0.3
        required_base = rng.random((shapes, 4)) < 0.2
        taints = rng.random((types, 4)) < 0.3
        labels = rng.random((types, 4)) < 0.7
        full = make_inputs(
            full_req, alloc,
            pod_intolerant=np.repeat(intol_base, counts, axis=0),
            pod_required=np.repeat(required_base, counts, axis=0),
            group_taints=taints, group_labels=labels,
        )
        dedup = make_inputs(
            base_req, alloc,
            pod_intolerant=intol_base, pod_required=required_base,
            group_taints=taints, group_labels=labels,
        )
        import dataclasses

        dedup = dataclasses.replace(
            dedup, pod_weight=jnp.asarray(counts.astype(np.int32))
        )
        return full, dedup, counts

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_weighted_equals_expanded(self, seed):
        rng = np.random.default_rng(seed)
        full, dedup, counts = self._random_dup_inputs(rng)
        a = B.binpack(full, buckets=16)
        b = B.binpack(dedup, buckets=16)
        np.testing.assert_array_equal(
            np.asarray(a.assigned_count), np.asarray(b.assigned_count)
        )
        np.testing.assert_array_equal(
            np.asarray(a.nodes_needed), np.asarray(b.nodes_needed)
        )
        np.testing.assert_array_equal(
            np.asarray(a.lp_bound), np.asarray(b.lp_bound)
        )
        assert int(a.unschedulable) == int(b.unschedulable)
        assert int(np.sum(np.asarray(b.assigned_count))) + int(
            b.unschedulable
        ) == int(np.sum(counts))

    def test_zero_weight_rows_are_inert(self):
        req = np.full((4, 2), 0.5, np.float32)
        inputs = B.BinPackInputs(
            pod_requests=jnp.asarray(req),
            pod_valid=jnp.ones(4, bool),
            pod_intolerant=jnp.zeros((4, 4), bool),
            pod_required=jnp.zeros((4, 4), bool),
            group_allocatable=jnp.asarray([[4.0, 4.0]], np.float32),
            group_taints=jnp.zeros((1, 4), bool),
            group_labels=jnp.zeros((1, 4), bool),
            pod_weight=jnp.asarray([3, 0, 0, 5], np.int32),
        )
        out = B.binpack(inputs, buckets=8)
        assert out.assigned_count.tolist() == [8]
        assert out.nodes_needed.tolist() == [1]


class TestMultiClusterRepack:
    def test_pinned_pods_stay_home_flexible_cross(self):
        """BASELINE config 5 (bench.py --clusters): the cluster boundary
        is a required-label constraint — pinned pods must land on their
        home cluster's groups; flexible pods may re-pack anywhere."""
        import bench

        pods, clusters, tpc = 600, 4, 5
        inputs = bench.build_multicluster_inputs(
            pods, clusters, tpc, taints=8, labels=12, seed=3
        )
        out = B.binpack(inputs, buckets=16)
        assigned = np.asarray(out.assigned)
        required = np.asarray(inputs.pod_required)
        crossed = 0
        for p in range(pods):
            t = int(assigned[p])
            if t < 0:
                continue
            cluster_of_group = t // tpc
            pinned_to = np.flatnonzero(required[p, :clusters])
            if len(pinned_to):
                assert cluster_of_group == int(pinned_to[0]), (
                    p, t, pinned_to
                )
            elif cluster_of_group != 0:
                crossed += 1
        assert crossed > 0  # flexible pods actually used other clusters
