"""Device-resident fleet state (solver/resident.py) — the resident
scatter-update path must be BIT-IDENTICAL to a cold full re-encode +
full upload, across every churn shape the control plane produces.

Structure: a ResidentWorld drives the REAL pipeline — watch-style churn
into a PendingPodCache, delta encoding through a SnapshotDeltaCache
(which publishes the scatter plans), and dispatch through a
SolverService whose residency layer consumes them. Every tick asserts
three ways:

  * the service's outputs equal a resident-OFF service's outputs on the
    SAME inputs (device path) and the numpy mirror's outputs (integer
    fields exact, lp_bound within the established ±1 contract — though
    on the same backend it is in fact equal);
  * the RESIDENT DEVICE BUFFERS equal pad_to_bucket(cold full encode)
    leaf for leaf, byte for byte — the direct pin that scattering
    changed rows reproduces the full upload exactly;
  * the residency counters report the expected serve kind (hit /
    scatter / rebuild), so the fast path can't silently rot into
    rebuild-every-tick while outputs stay green.
"""

import dataclasses

import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from karpenter_tpu.metrics.producers.pendingcapacity import encoder as E
from karpenter_tpu.metrics.producers.pendingcapacity.encoder import (
    SnapshotDeltaCache,
    _encode_full,
    resident_plan,
)
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops.numpy_binpack import binpack_numpy
from karpenter_tpu.solver import SolverService
from karpenter_tpu.solver.bucketing import pad_to_bucket
from karpenter_tpu.store.columnar import PendingPodCache
from karpenter_tpu.utils.quantity import Quantity

BUCKETS = 8


def pod(name, cpu="100m", mem="128Mi", selector=None):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            containers=[Container(requests={
                "cpu": Quantity.parse(cpu),
                "memory": Quantity.parse(mem),
            })],
            node_selector=dict(selector or {}),
        ),
        status=PodStatus(phase="Pending"),
    )


def make_profiles():
    """Stable profile tuples — reused across ticks like NodeMirror's
    memo, which is what arms the delta cache's identity check."""
    return [
        ({"cpu": 8.0, "memory": 32.0 * 1024**3, "pods": 110.0},
         {("zone", "z"), ("group", "a")}, set()),
        ({"cpu": 64.0, "memory": 256.0 * 1024**3, "pods": 110.0},
         {("group", "b")}, set()),
    ]


class ResidentWorld:
    """One tenant's real encode->solve pipeline with residency ON, plus
    a residency-OFF reference service for output parity."""

    def __init__(self, shard_threshold=0):
        self.cache = PendingPodCache(store=None, capacity=64)
        self.profiles = make_profiles()
        self.delta = SnapshotDeltaCache()
        self.svc = SolverService(
            registry=GaugeRegistry(), shard_threshold=shard_threshold,
        )
        # force the scatter rung: the auto gate keeps it off CPU
        # "devices" (scatter ~= upload there), but these tests PIN the
        # scatter math itself and run on the virtual-CPU harness
        self.svc._resident.scatter = "always"
        self.ref = SolverService(
            registry=GaugeRegistry(), shard_threshold=0, resident=False,
        )

    def close(self):
        self.svc.close()
        self.ref.close()

    def upsert(self, p):
        self.cache._upsert((p.metadata.namespace, p.metadata.name), p)

    def remove(self, name):
        self.cache._remove(("default", name))

    def tick(self, expect=None):
        """Encode + solve one tick; assert output parity (device ref +
        numpy mirror) and resident-buffer parity vs the cold encode."""
        snap = self.cache.snapshot()
        inputs = self.delta.encode(snap, self.profiles)
        before = (
            self.svc.stats.resident_hits,
            self.svc.stats.resident_scatters,
            self.svc.stats.resident_rebuilds,
        )
        out = self.svc.solve(inputs, buckets=BUCKETS, backend="xla")
        cold = _encode_full(snap, self.profiles)
        ref = self.ref.solve(cold, buckets=BUCKETS, backend="xla")
        ref_np = binpack_numpy(cold, buckets=BUCKETS)
        for mirror, label in ((ref, "xla"), (ref_np, "numpy")):
            np.testing.assert_array_equal(
                out.assigned, np.asarray(mirror.assigned), err_msg=label
            )
            np.testing.assert_array_equal(
                out.assigned_count, np.asarray(mirror.assigned_count),
                err_msg=label,
            )
            np.testing.assert_array_equal(
                out.nodes_needed, np.asarray(mirror.nodes_needed),
                err_msg=label,
            )
            assert int(out.unschedulable) == int(mirror.unschedulable)
        self._assert_buffers_equal_cold(inputs, cold)
        if expect is not None:
            after = (
                self.svc.stats.resident_hits,
                self.svc.stats.resident_scatters,
                self.svc.stats.resident_rebuilds,
            )
            deltas = tuple(b - a for a, b in zip(before, after))
            want = {
                "hit": (1, 0, 0),
                "scatter": (0, 1, 0),
                "rebuild": (0, 0, 1),
            }[expect]
            assert deltas == want, (expect, deltas)
        return out

    def _assert_buffers_equal_cold(self, inputs, cold):
        """The strong pin: the resident device buffers byte-equal the
        padded cold encode — scattering reproduced the full upload."""
        entry = None
        with self.svc._resident._lock:
            for e in self.svc._resident._entries.values():
                if e.host is inputs:
                    entry = e
        if entry is None:
            return  # served without residency (e.g. coalesced) — outputs
            # parity above still holds
        padded = pad_to_bucket(cold, entry.shape[:5])
        for field in dataclasses.fields(padded):
            want = getattr(padded, field.name)
            got = getattr(entry.stacked, field.name)
            if want is None or got is None:
                assert want is None and got is None, field.name
                continue
            np.testing.assert_array_equal(
                np.asarray(got)[0], np.asarray(want),
                err_msg=field.name,
            )


@pytest.fixture
def world():
    w = ResidentWorld()
    yield w
    w.close()


class TestResidentChurn:
    def test_unchanged_fleet_is_identity_hit(self, world):
        for i in range(12):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        # no churn: the SAME inputs object comes back (delta-cache
        # memo) and the dispatch serves the resident buffers untouched
        world.tick(expect="hit")
        world.tick(expect="hit")

    def test_add_remove_rows_scatter(self, world):
        for i in range(16):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        world.upsert(pod("fresh-a", cpu="900m"))
        world.upsert(pod("fresh-b", cpu="901m"))
        world.tick(expect="scatter")
        world.remove("p3")
        world.remove("p7")
        world.tick(expect="scatter")

    def test_resize_and_relabel_rows_scatter(self, world):
        for i in range(12):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m",
                             selector={"zone": "z"}))
        # pre-intern the second label pair so the later relabel stays
        # inside one label universe (universe growth is a full-pass
        # cache-key change by design, not a delta)
        world.upsert(pod("seed", cpu="400m", selector={"group": "a"}))
        world.tick(expect="rebuild")
        # resize: same pod, new request vector
        world.upsert(pod("p4", cpu="750m", selector={"zone": "z"}))
        world.tick(expect="scatter")
        # relabel within the existing label universe: selectors move to
        # the already-interned label pair
        world.upsert(pod("p5", cpu="105m", selector={"group": "a"}))
        world.upsert(pod("p0", cpu="100m", selector={"group": "a"}))
        world.tick(expect="scatter")

    def test_weight_only_churn_scatter(self, world):
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        # a replica of an EXISTING shape: dedup keys unchanged, only
        # the multiplicity column moves
        world.upsert(pod("p3-replica", cpu="103m"))
        inputs = world.delta.encode(
            world.cache.snapshot(), world.profiles
        )
        plan = resident_plan(inputs)
        assert plan is not None
        assert len(plan.weight_rows) >= 1
        world.tick(expect="scatter")

    def test_group_churn_full_reencode_rebuild(self, world):
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        # node churn: NEW profile tuples (identity differs) force the
        # delta cache through the full pass — no plan, residency
        # rebuilds, outputs still exact
        world.profiles = make_profiles()
        world.upsert(pod("extra", cpu="500m"))
        world.tick(expect="rebuild")

    def test_recovery_restart_drops_residency(self, world):
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        world.upsert(pod("fresh", cpu="800m"))
        # the recovery-boot seam: service caches + delta entries +
        # scatter plans all drop; the next tick is a cold rebuild and
        # the one after scatters again
        world.svc.reset_caches()
        world.delta.reset()
        assert world.svc._resident.resident_bytes() == 0
        world.tick(expect="rebuild")
        world.upsert(pod("fresh-2", cpu="801m"))
        world.tick(expect="scatter")

    def test_tenant_chains_keep_separate_entries(self):
        a, b = ResidentWorld(), ResidentWorld()
        # one SHARED service (the multi-tenant topology): each tenant's
        # identity chain occupies its own resident entry
        b.svc.close()
        b.svc = a.svc
        b.ref.close()
        b.ref = a.ref
        try:
            for i in range(8):
                a.upsert(pod(f"a{i}", cpu=f"{100 + i}m"))
                b.upsert(pod(f"b{i}", cpu=f"{300 + i}m"))
            a.tick(expect="rebuild")
            b.tick(expect="rebuild")
            # interleaved unchanged ticks: both chains stay resident
            a.tick(expect="hit")
            b.tick(expect="hit")
            # tenant churn scatters its own chain only
            a.upsert(pod("a-new", cpu="950m"))
            a.tick(expect="scatter")
            b.tick(expect="hit")
            # tenant removal: b's chain simply stops being dispatched;
            # a keeps serving resident
            a.tick(expect="hit")
        finally:
            a.close()


class TestShardThresholdCrossing:
    def test_crossing_rebuilds_then_scatters_both_modes(self):
        w = ResidentWorld(shard_threshold=1 << 60)
        try:
            for i in range(16):
                w.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
            w.tick(expect="rebuild")  # single-device mode
            w.tick(expect="hit")
            # cross UP: the same fleet now routes through the mesh —
            # mode changes, residency rebuilds under NamedShardings
            w.svc.shard_threshold = 1
            w.upsert(pod("up-a", cpu="700m"))
            w.tick(expect="rebuild")
            assert w.svc.stats.shard_dispatches >= 1
            w.upsert(pod("up-b", cpu="701m"))
            w.tick(expect="scatter")  # sharded-mode scatter
            w.tick(expect="hit")
            # cross DOWN: back to the single-device program — mode
            # changes again, residency rebuilds again
            w.svc.shard_threshold = 1 << 60
            w.upsert(pod("down-a", cpu="702m"))
            w.tick(expect="rebuild")
            w.tick(expect="hit")
        finally:
            w.close()


class TestNeverBlock:
    def test_device_failure_drops_residency_and_recovers(self, world):
        from karpenter_tpu.faults import injected_faults

        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        with injected_faults(seed=7) as reg:
            reg.plan("solver.dispatch", mode="error")
            world.upsert(pod("during-fault", cpu="600m"))
            snap = world.cache.snapshot()
            inputs = world.delta.encode(snap, world.profiles)
            out = world.svc.solve(inputs, buckets=BUCKETS, backend="xla")
            ref = binpack_numpy(
                _encode_full(snap, world.profiles), buckets=BUCKETS
            )
            np.testing.assert_array_equal(out.assigned, ref.assigned)
            # the ladder discarded residency wholesale
            assert world.svc._resident.resident_bytes() == 0
        # post-fault: the next tick re-establishes residency cold
        world.upsert(pod("after-fault", cpu="601m"))
        world.tick(expect="rebuild")
        world.tick(expect="hit")

    def test_poisoned_plan_falls_back_to_rebuild(self, world):
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        world.upsert(pod("fresh", cpu="888m"))
        snap = world.cache.snapshot()
        inputs = world.delta.encode(snap, world.profiles)
        plan = resident_plan(inputs)
        assert plan is not None
        # poison: rows past the resident extent must rebuild, not raise
        plan.rows = np.asarray([10**6], np.int32)
        world.tick(expect="rebuild")


class TestUnchangedTickSkipsEncodeAndUpload:
    """The bench-resident regression guard (non-slow): an unchanged
    fleet tick costs zero encode and zero upload."""

    def test_unchanged_tick_zero_encode_zero_upload(self, world):
        for i in range(12):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        snap = world.cache.snapshot()
        first = world.delta.encode(snap, world.profiles)
        world.svc.solve(first, buckets=BUCKETS, backend="xla")
        fulls_before = world.delta.fulls
        uploads_before = list(world.svc._stages.get("upload", ()))
        # the unchanged tick: same snapshot generation -> same inputs
        # OBJECT from the delta memo -> resident identity hit
        again = world.delta.encode(world.cache.snapshot(), world.profiles)
        assert again is first  # zero host encode
        world.svc.solve(again, buckets=BUCKETS, backend="xla")
        assert world.delta.fulls == fulls_before  # no full pass
        assert world.svc.stats.resident_hits >= 1
        # the upload ring gained only the 0.0 marker — nothing crossed
        # the transfer link for this dispatch
        uploads = list(world.svc._stages["upload"])
        new = uploads[len(uploads_before):]
        assert new and max(new) == 0.0

    def test_resident_gauges_exposed(self, world):
        for i in range(8):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        world.svc.publish_gauges()
        reg = world.svc.registry
        assert reg.gauge("solver", "resident_bytes").get("-", "-") > 0
        assert reg.gauge("solver", "resident_rows").get("-", "-") > 0


class TestEntryLifecycle:
    def test_scatter_chain_keeps_one_live_entry(self, world):
        """A superseded predecessor is EVICTED when its successor
        stores (scatter and rebuild rungs alike): one churning chain
        must occupy one LRU slot, not fill MAX_ENTRIES with dead
        stacks that would evict other tenants' live chains."""
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        for k in range(2 * world.svc._resident.MAX_ENTRIES):
            world.upsert(pod(f"churn-{k}", cpu=f"{500 + k}m"))
            world.tick(expect="scatter")
        with world.svc._resident._lock:
            assert len(world.svc._resident._entries) == 1
        # and the CPU auto-gated rebuild rung evicts the same way
        world.svc._resident.scatter = "auto"
        for k in range(3):
            world.upsert(pod(f"auto-{k}", cpu=f"{700 + k}m"))
            world.tick(expect="rebuild")
        with world.svc._resident._lock:
            assert len(world.svc._resident._entries) == 1


class TestScatterAutoGate:
    def test_cpu_auto_mode_rebuilds_instead_of_scattering(self, world):
        """The shipped default: on a CPU jax backend the scatter rung
        stays OFF (device memory IS host memory — a copy-on-write
        scatter costs what the memcpy upload costs), so churn rebuilds;
        identity hits still serve with zero upload."""
        world.svc._resident.scatter = "auto"
        for i in range(10):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.tick(expect="rebuild")
        world.tick(expect="hit")
        world.upsert(pod("fresh", cpu="900m"))
        world.tick(expect="rebuild")  # plan exists but the gate holds
        world.tick(expect="hit")


class TestPlanRegistry:
    def test_plan_chain_is_bounded(self, world):
        """Successive deltas must not chain prev references without
        bound: registering tick k's plan drops tick k-1's entry."""
        for i in range(8):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        prev_inputs = world.delta.encode(
            world.cache.snapshot(), world.profiles
        )
        for k in range(4):
            world.upsert(pod(f"churn-{k}", cpu=f"{500 + k}m"))
            inputs = world.delta.encode(
                world.cache.snapshot(), world.profiles
            )
            assert resident_plan(inputs) is not None
            assert resident_plan(prev_inputs) is None
            prev_inputs = inputs

    def test_reset_clears_plans(self, world):
        for i in range(8):
            world.upsert(pod(f"p{i}", cpu=f"{100 + i}m"))
        world.delta.encode(world.cache.snapshot(), world.profiles)
        world.upsert(pod("x", cpu="400m"))
        inputs = world.delta.encode(world.cache.snapshot(), world.profiles)
        assert resident_plan(inputs) is not None
        E.reset_delta_cache()
        world.delta.reset()
        assert resident_plan(inputs) is None
