"""Existing-pod domain occupancy: the pending-pods signal evaluated
against the pods the cluster has ALREADY placed.

The kube-scheduler counts existing matching pods per topology domain
when it checks topologySpreadConstraints skew and required inter-pod
(anti-)affinity; a signal that ignores them can promise a placement
(e.g. a replica into a zone that already holds one) the scheduler then
refuses. store/columnar.ScheduledOccupancy maintains the census
incrementally; producers/pendingcapacity.DomainCensus answers the
spread/anti expansions.

reference anchor: the reference stubs the whole producer
(pendingcapacity/producer.go:29-31); the fidelity bar here is the
kube-scheduler's PodTopologySpread and InterPodAffinity filters.
"""

import pytest

from karpenter_tpu.api.core import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PodStatus,
    TopologySpreadConstraint,
    resource_list,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.runtime import KarpenterRuntime

ZONE_KEY = "topology.kubernetes.io/zone"


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


@pytest.fixture
def env():
    provider = FakeFactory()
    clock = FakeClock()
    runtime = KarpenterRuntime(cloud_provider_factory=provider, clock=clock)
    runtime.clock = clock
    return runtime, provider


def ready_node(name, labels, cpu="64", memory="64Gi", pods="110"):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu=cpu, memory=memory, pods=pods),
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pending_mp(name, selector):
    return MetricsProducer(
        metadata=ObjectMeta(name=name),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(node_selector=dict(selector))
        ),
    )


def bound_pod(name, labels, node, phase="Running", namespace="default"):
    """A pod the scheduler already placed — the occupancy the census
    counts (assigned and not terminal)."""
    return Pod(
        metadata=ObjectMeta(
            name=name, namespace=namespace, labels=dict(labels)
        ),
        spec=PodSpec(
            node_name=node,
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
        ),
        status=PodStatus(phase=phase),
    )


def spread_pod(name, labels, selector=None, max_skew=1, min_domains=None,
               node_selector=None):
    """A pending pod with one hard zone-spread constraint; selector
    defaults to the pod's own labels (the realistic workload shape)."""
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
            node_selector=dict(node_selector or {}),
        ),
    )
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=max_skew,
            topology_key=ZONE_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector={
                "matchLabels": dict(selector if selector is not None
                                    else labels)
            },
            min_domains=min_domains,
        )
    ]
    return pod


def anti_pod(name, labels=None, keys=(ZONE_KEY,), co_keys=(),
             selector_labels=None):
    """A pending pod with required self-anti-affinity on `keys` and
    required self-affinity (co-location) on `co_keys`."""
    labels = dict(labels or {"app": "db"})
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
        ),
    )
    selector = LabelSelector(
        match_labels=dict(selector_labels or labels)
    )
    pod.spec.affinity = Affinity(
        pod_anti_affinity=(
            PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    PodAffinityTerm(label_selector=selector, topology_key=k)
                    for k in keys
                ]
            )
            if keys
            else None
        ),
        pod_affinity=(
            PodAffinity(
                required_during_scheduling_ignored_during_execution=[
                    PodAffinityTerm(label_selector=selector, topology_key=k)
                    for k in co_keys
                ]
            )
            if co_keys
            else None
        ),
    )
    return pod


def zoned(runtime, zones=("a", "b"), extra_node_labels=None):
    for z in zones:
        labels = {"group": z, ZONE_KEY: f"us-{z}"}
        labels.update(extra_node_labels or {})
        runtime.store.create(ready_node(f"n-{z}", labels))
        runtime.store.create(pending_mp(f"group-{z}", {"group": z}))


def pods_per_group(runtime, names):
    return {
        n: runtime.store.get("MetricsProducer", "default", n)
        .status.pending_capacity.pending_pods
        for n in names
    }


def total_unschedulable(runtime, name):
    return (
        runtime.store.get("MetricsProducer", "default", name)
        .status.pending_capacity.unschedulable_pods
    )


class TestWaterFill:
    """Property tests of the water-fill against a scalar placement
    oracle: place pods ONE AT A TIME into a current-minimum domain
    (the only order the kube-scheduler skew check always admits) and
    compare final totals."""

    def _oracle(self, counts, caps, schedulable):
        totals = list(counts)
        placed = [0] * len(counts)
        for _ in range(schedulable):
            candidates = [
                j
                for j in range(len(totals))
                if caps is None or placed[j] < caps[j]
            ]
            if not candidates:
                break
            j = min(candidates, key=lambda j: (totals[j], j))
            totals[j] += 1
            placed[j] += 1
        return placed

    def test_matches_scalar_oracle_totals(self):
        import numpy as np

        from karpenter_tpu.metrics.producers.pendingcapacity.partition import (
            _water_fill,
        )

        rng = np.random.default_rng(7)
        for trial in range(300):
            d = int(rng.integers(1, 9))
            counts = rng.integers(0, 12, d).tolist()
            caps = (
                None
                if rng.random() < 0.3
                else rng.integers(0, 10, d).tolist()
            )
            capacity = (
                10 ** 9 if caps is None else int(sum(caps))
            )
            schedulable = min(int(rng.integers(0, 40)), capacity)
            got = _water_fill(counts, caps, schedulable, int(rng.integers(0, 97)))
            assert int(got.sum()) == schedulable
            if caps is not None:
                assert (got <= np.asarray(caps)).all()
            # water-filling and lowest-first placement agree on the
            # FINAL LEVELS (multiset of totals); the remainder rotation
            # may pick different equal-level domains than the oracle's
            # index tie-break, so compare sorted totals
            oracle = self._oracle(counts, caps, schedulable)
            assert sorted(
                c + int(g) for c, g in zip(counts, got)
            ) == sorted(c + p for c, p in zip(counts, oracle))

    def test_every_placement_is_skew_legal(self):
        """Replaying the water-fill result lowest-first never places
        into a domain more than maxSkew above the running minimum —
        the incremental admissibility the split promises. Modeled with
        caps = m_out + skew - c (the frozen-outside-minimum rule)."""
        import numpy as np

        from karpenter_tpu.metrics.producers.pendingcapacity.partition import (
            _water_fill,
        )

        rng = np.random.default_rng(11)
        for trial in range(200):
            d = int(rng.integers(1, 7))
            skew = int(rng.integers(1, 4))
            counts = rng.integers(0, 8, d).tolist()
            m_out = int(rng.integers(0, 8))
            caps = [max(0, m_out + skew - c) for c in counts]
            schedulable = min(int(rng.integers(0, 30)), sum(caps))
            got = _water_fill(counts, caps, schedulable, trial)
            totals = list(counts)
            remaining = [int(g) for g in got]
            for _ in range(schedulable):
                # place into the lowest destination domain still owed
                j = min(
                    (j for j in range(d) if remaining[j]),
                    key=lambda j: (totals[j], j),
                )
                global_min = min([*totals, m_out])
                assert totals[j] + 1 - global_min <= skew
                totals[j] += 1
                remaining[j] -= 1


class TestScheduledOccupancy:
    """The incremental census itself (store/columnar)."""

    def _store(self):
        from karpenter_tpu.store.store import Store

        return Store()

    def test_counts_bound_nonterminal_pods_only(self):
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        store = self._store()
        census = ScheduledOccupancy(store)
        store.create(bound_pod("running", {"app": "web"}, "n1"))
        store.create(bound_pod("done", {"app": "web"}, "n1",
                               phase="Succeeded"))
        store.create(bound_pod("crashed", {"app": "web"}, "n1",
                               phase="Failed"))
        store.create(
            Pod(metadata=ObjectMeta(name="pending",
                                    labels={"app": "web"}),
                spec=PodSpec(node_name=""))
        )
        with census.view() as (_, spaces):
            key = ("default", (("app", "web"),))
            assert spaces["default"][key[1]] == {"n1": 1}

    def test_rebind_and_delete_undo_exactly(self):
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        store = self._store()
        census = ScheduledOccupancy(store)
        pod = bound_pod("p", {"app": "web"}, "n1")
        store.create(pod)
        g1 = census.generation
        moved = bound_pod("p", {"app": "web"}, "n2")
        moved.metadata.resource_version = pod.metadata.resource_version
        store.update(moved)
        with census.view() as (_, spaces):
            assert spaces["default"][(("app", "web"),)] == {"n2": 1}
        assert census.generation > g1
        store.delete("Pod", "default", "p")
        with census.view() as (_, spaces):
            assert spaces == {}

    def test_no_op_update_keeps_generation(self):
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        store = self._store()
        census = ScheduledOccupancy(store)
        pod = bound_pod("p", {"app": "web"}, "n1")
        store.create(pod)
        g = census.generation
        census._on_event("Modified", pod)  # same placement
        assert census.generation == g

    def test_view_cap_evicts_lru_and_counts(self):
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        store = self._store()
        census = ScheduledOccupancy(store)
        store.create(bound_pod("p", {"app": "a0"}, "n1"))
        cap = ScheduledOccupancy.VIEW_CAP
        for i in range(cap + 3):
            census.view_counts(
                "default", ((("app", f"a{i}"),), ())
            )
        assert census.view_evictions == 3
        # the oldest views were evicted; the newest still resolve from
        # the live set and stay maintained by the event path
        _, counts = census.view_counts("default", ((("app", "a0"),), ()))
        assert counts == {"n1": 1}

    def test_view_counts_many_is_single_generation(self):
        """Multi-form reads share one lock hold: the returned set is
        generation-consistent by construction (a replica moving nodes
        between reads can't appear on neither)."""
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        store = self._store()
        census = ScheduledOccupancy(store)
        store.create(bound_pod("p", {"app": "x", "tier": "db"}, "n1"))
        generation, per_form = census.view_counts_many(
            "default",
            (((("app", "x"),), ()), ((("tier", "db"),), ())),
        )
        assert per_form == [{"n1": 1}, {"n1": 1}]
        assert generation == census.generation

    def test_detached_matches_watch_maintained(self):
        from karpenter_tpu.store.columnar import (
            ScheduledOccupancy,
            occupancy_from_pods,
        )

        store = self._store()
        census = ScheduledOccupancy(store)
        for i in range(3):
            store.create(bound_pod(f"p{i}", {"app": "web"}, f"n{i % 2}"))
        oracle = occupancy_from_pods(store.list("Pod"))
        with census.view() as (_, live):
            with oracle.view() as (_, detached):
                assert live == detached


class TestSpreadOccupancy:
    """Water-filled spread splits against existing per-domain counts."""

    def test_new_replicas_fill_less_loaded_domains(self, env):
        """2 existing replicas in zone a: 4 new ones go 1/3 so final
        totals level at 3/3 — the scheduler's skew check admits exactly
        the least-loaded-first order."""
        runtime, _ = env
        zoned(runtime)
        for i in range(2):
            runtime.store.create(
                bound_pod(f"old{i}", {"app": "web"}, "n-a")
            )
        for i in range(4):
            runtime.store.create(spread_pod(f"new{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 3,
        }
        assert total_unschedulable(runtime, "group-a") == 0

    def test_unfillable_outside_domain_caps_by_skew(self, env):
        """A zone among filter-passing live nodes that NO candidate
        group serves freezes the global minimum (the well-known k8s
        spread footgun): each eligible domain caps at outside-min +
        maxSkew, the excess is unschedulable."""
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c"})
        )
        for i in range(5):
            runtime.store.create(spread_pod(f"p{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 1,
        }
        assert total_unschedulable(runtime, "group-a") == 3

    def test_node_filter_excludes_outside_domain(self, env):
        """Same topology, but the pods' nodeSelector excludes the
        unmanaged node (nodeAffinityPolicy=Honor): its zone defines no
        domain for these pods and the split is plain balanced."""
        runtime, _ = env
        zoned(runtime, extra_node_labels={"tier": "app"})
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c"})
        )
        for i in range(5):
            runtime.store.create(
                spread_pod(f"p{i}", {"app": "web"},
                           node_selector={"tier": "app"})
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [2, 3]
        assert total_unschedulable(runtime, "group-a") == 0

    def test_mixed_nil_and_set_soft_selectors_do_not_crash(self, env):
        """Two rows of one hard-spread workload whose SOFT rack
        constraints differ only in selector presence (nil labelSelector
        vs a set one): the canonical row-key ordering used for multi-row
        hand-out must stay totally ordered — None-vs-tuple selector
        forms inside shape tuples crashed every reconcile with TypeError
        before _total_order (r3 advisor, high)."""
        runtime, _ = env
        zoned(runtime)
        rack = "topology.kubernetes.io/rack"
        soft = [
            None,  # nil labelSelector: counts nothing (metav1 nil)
            {"matchLabels": {"app": "web"}},
        ]
        for i, sel in enumerate(soft):
            pod = spread_pod(f"p{i}", {"app": "web"})
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=rack,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=sel,
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the reconcile completes and the hard zone spread still splits
        # the two replicas across the zones
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 1,
        }
        assert total_unschedulable(runtime, "group-a") == 0

    def test_same_key_different_selectors_enforce_own_counts(self, env):
        """Two DoNotSchedule constraints on the SAME topology key with
        DIFFERENT selectors: each selector's skew binds against its OWN
        census counts. 4 bound tier=backend pods sit in zone a; 2
        pending {app:web, tier:backend} pods carry maxSkew-1 zone
        constraints on both selectors. tier=backend forbids zone a
        (4+1-min > 1) and app=web forbids a second pod in zone b
        (0+2-0 > 1): exactly one pod schedules, into zone b. The
        pre-fix signal promised a pod into zone a — a placement the
        scheduler's second skew check denies (r3 advisor, medium)."""
        runtime, _ = env
        zoned(runtime)
        for i in range(4):
            runtime.store.create(
                bound_pod(f"old{i}", {"tier": "backend"}, "n-a")
            )
        for i in range(2):
            pod = spread_pod(
                f"p{i}", {"app": "web", "tier": "backend"},
                selector={"app": "web"},
            )
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"tier": "backend"}},
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }
        assert total_unschedulable(runtime, "group-a") == 1

    def test_min_domains_cap_subtracts_existing(self, env):
        """minDomains unsatisfied treats the global minimum as 0: each
        domain holds at most maxSkew matching pods INCLUDING existing
        ones."""
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(bound_pod("old", {"app": "web"}, "n-a"))
        for i in range(6):
            runtime.store.create(
                spread_pod(f"p{i}", {"app": "web"}, max_skew=2,
                           min_domains=3)
            )
        runtime.manager.reconcile_all()
        # caps: zone a 2-1=1, zone b 2-0=2 -> 3 schedulable, 3 stuck
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 2,
        }
        assert total_unschedulable(runtime, "group-a") == 3

    def test_non_self_matching_selector_is_static_exclusion(self, env):
        """A pod that does not match its own constraint's selector never
        moves the counts (selfMatchNum=0): domains whose existing skew
        already exceeds maxSkew are excluded, the rest are unbounded."""
        runtime, _ = env
        zoned(runtime)
        for i in range(2):
            runtime.store.create(
                bound_pod(f"other{i}", {"app": "other"}, "n-a")
            )
        for i in range(4):
            runtime.store.create(
                spread_pod(f"p{i}", {"app": "web"},
                           selector={"app": "other"})
            )
        runtime.manager.reconcile_all()
        # zone a holds skew 2 > maxSkew 1 over zone b's 0: excluded
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 4,
        }

    def test_node_affinity_policy_ignore_counts_all_nodes(self, env):
        """nodeAffinityPolicy: Ignore — the unmanaged node's zone
        defines a domain even though the pods' nodeSelector excludes
        it, so the frozen-minimum cap applies (the inverse of
        test_node_filter_excludes_outside_domain)."""
        runtime, _ = env
        zoned(runtime, extra_node_labels={"tier": "app"})
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c"})
        )
        for i in range(5):
            pod = spread_pod(f"p{i}", {"app": "web"},
                             node_selector={"tier": "app"})
            pod.spec.topology_spread_constraints[0].node_affinity_policy = (
                "Ignore"
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 1,
        }
        assert total_unschedulable(runtime, "group-a") == 3

    def test_anti_census_is_fresh_across_ticks(self, env):
        """Regression (r3 code review): the census memo must be dropped
        when occupancy changes — a replica bound between ticks spends
        its domain on the very next solve, on the PERSISTENT feed-path
        census."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i in range(2):
            runtime.store.create(anti_pod(f"db-{i}"))
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [1, 1]
        # one replica lands: bind it where the solver put it (zone a),
        # and keep the OTHER one pending
        runtime.store.delete("Pod", "default", "db-0")
        runtime.store.create(
            bound_pod(
                "db-0",
                {"app": "db"},
                "n-a",
            )
        )
        runtime.clock.advance(6)  # past the 5 s producer interval
        runtime.manager.reconcile_all()
        # zone a is now spent: the remaining replica must sit in b only
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_match_label_keys_refines_the_selector(self, env):
        """matchLabelKeys (the pod-template-hash pattern): the pod's own
        values for the listed keys AND into the selector, so a NEW
        revision spreads independently of the old one's placement."""
        runtime, _ = env
        zoned(runtime)
        for i in range(2):
            runtime.store.create(
                bound_pod(
                    f"v1-{i}",
                    {"app": "web", "pod-template-hash": "v1"},
                    "n-a",
                )
            )
        for i in range(4):
            pod = spread_pod(
                f"v2-{i}",
                {"app": "web", "pod-template-hash": "v2"},
                selector={"app": "web"},
            )
            pod.spec.topology_spread_constraints[0].match_label_keys = [
                "pod-template-hash"
            ]
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # v1's zone-a pods don't count against v2: plain balanced split
        # (without matchLabelKeys the water-fill would send 3 to b)
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [2, 2]

    def test_match_label_keys_missing_on_pod_is_ignored(self):
        from karpenter_tpu.api.core import (
            TopologySpreadConstraint,
            spread_shape,
        )

        constraint = TopologySpreadConstraint(
            max_skew=1,
            topology_key=ZONE_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector={"matchLabels": {"app": "web"}},
            match_label_keys=["pod-template-hash"],
        )
        with_key = spread_shape(
            [constraint], "default",
            {"app": "web", "pod-template-hash": "v2"},
        )
        without_key = spread_shape(
            [constraint], "default", {"app": "web"}
        )
        sel_with = with_key[1][0][3]
        sel_without = without_key[1][0][3]
        assert ("pod-template-hash", "v2") in sel_with[0]
        assert sel_without == ((("app", "web"),), ())

    def test_other_key_zero_capacity_domains_are_excluded(self, env):
        """Multi-key spread: the non-split key can't drive the split,
        but a domain of it with ZERO remaining capacity is a hard
        exclusion — replicas must not be promised to racks the second
        constraint already fills (r3)."""
        # sorts AFTER the zone key: the split must run on zone and
        # treat this as the non-split (budgeted) key
        rack = "x-topology.example.com/rack"
        runtime, _ = env
        for z, r in (("a", "r1"), ("b", "r2")):
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: f"us-{z}", rack: r},
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        # rack r1 already holds maxSkew matching pods under the
        # minDomains-unsatisfied rule (2 racks < minDomains 3)
        runtime.store.create(bound_pod("old", {"app": "web"}, "n-a"))
        for i in range(4):
            pod = spread_pod(f"p{i}", {"app": "web"})
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=rack,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": "web"}},
                    min_domains=3,
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # rack r1 (group-a) capped at 1-1=0 by the rack entry; the rack
        # total (0 + 1) also bounds schedulable at 1
        assert counts == {"group-a": 0, "group-b": 1}
        assert total_unschedulable(runtime, "group-a") == 3

    def test_other_key_without_existing_pods_is_unchanged(self, env):
        """No occupancy: the secondary key contributes key-presence
        exclusion only, exactly the prior behavior."""
        # sorts AFTER the zone key: the split must run on zone and
        # treat this as the non-split (budgeted) key
        rack = "x-topology.example.com/rack"
        runtime, _ = env
        for z, r in (("a", "r1"), ("b", "r2")):
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: f"us-{z}", rack: r},
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        for i in range(4):
            pod = spread_pod(f"p{i}", {"app": "web"})
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=rack,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": "web"}},
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [2, 2]
        assert total_unschedulable(runtime, "group-a") == 0

    def test_other_key_positive_caps_are_designated_not_overdrawn(
        self, env
    ):
        """Regression (r3 code review): positive finite capacity on a
        non-split key must bound the DISTRIBUTION, not just the total —
        each chunk pins to one of that key's domains and consumes its
        budget, so concentration can't overdraw a rack."""
        # sorts AFTER the zone key: the split must run on zone and
        # treat this as the non-split (budgeted) key
        rack = "x-topology.example.com/rack"
        runtime, _ = env
        for z, r in (("a", "r1"), ("b", "r2")):
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: f"us-{z}", rack: r},
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        # rack budget under the minDomains-unsatisfied rule (2 < 3),
        # selector tier=db: r1 admits 2, r2 admits 2-1=1
        runtime.store.create(bound_pod("old", {"tier": "db"}, "n-b"))
        for i in range(4):
            pod = spread_pod(
                f"p{i}", {"app": "web", "tier": "db"},
                selector={"app": "web"},
            )
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=2,
                    topology_key=rack,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"tier": "db"}},
                    min_domains=3,
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # zone split is balanced [2, 2]; rack budgets cap group-a at 2
        # and group-b at 1 — the 4th replica must NOT be promised to
        # rack r2 just because the total (3) had room elsewhere
        assert counts == {"group-a": 2, "group-b": 1}
        assert total_unschedulable(runtime, "group-a") == 1

    def test_rows_with_different_node_filters_share_the_budget(
        self, env
    ):
        """Regression (r3 code review): a mid-rollout workload whose new
        revision adds a nodeSelector still spends ONE budget — per-(row
        filter) cap views must not each get a fresh ledger."""
        runtime, _ = env
        zoned(runtime, extra_node_labels={"tier": "app"})
        # unmanaged empty zone passing BOTH rows' filters: every row's
        # view caps each zone at maxSkew=1 total for the workload
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c", "tier": "app"})
        )
        for i in range(2):
            runtime.store.create(
                spread_pod(f"plain-{i}", {"app": "web"})
            )
        for i in range(2):
            runtime.store.create(
                spread_pod(
                    f"selector-{i}", {"app": "web"},
                    node_selector={"tier": "app"},
                )
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 2
        assert total_unschedulable(runtime, "group-a") == 2

    def test_dead_split_domain_freezes_the_minimum(self, env):
        """Regression (r3 code review): a split domain whose groups are
        all excluded by a non-split key is unfillable — it freezes the
        split-key global minimum, capping the surviving domains at its
        count + maxSkew, exactly like an unfillable outside zone."""
        # sorts AFTER the zone key: the split must run on zone and
        # treat this as the non-split (budgeted) key
        rack = "x-topology.example.com/rack"
        runtime, _ = env
        for z, r in (("a", "r1"), ("b", "r2")):
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: f"us-{z}", rack: r},
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        # rack r1 already violates the foreign-selector rack constraint
        for i in range(2):
            runtime.store.create(
                bound_pod(f"db-{i}", {"tier": "db"}, "n-a")
            )
        for i in range(4):
            pod = spread_pod(f"p{i}", {"app": "web"})
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=rack,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"tier": "db"}},
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # zone a is dead (rack r1 over-skewed for the rack selector);
        # its frozen count 0 caps zone b at 0 + maxSkew = 1 — NOT the 2
        # a balanced-then-masked split would promise
        assert counts == {"group-a": 0, "group-b": 1}
        assert total_unschedulable(runtime, "group-a") == 3

    def test_rows_of_one_workload_share_the_budget(self, env):
        """Regression (r3 code review): a workload split across
        request-distinct rows (mid-VPA) draws from ONE budget — two
        rows must not each spend the same per-domain capacity."""
        runtime, _ = env
        zoned(runtime)
        # empty zone c among filter-passing nodes freezes the global
        # minimum: each zone admits maxSkew=1 new replicas TOTAL
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c"})
        )
        for i in range(2):
            runtime.store.create(
                spread_pod(f"small-{i}", {"app": "web"})
            )
        for i in range(2):
            pod = spread_pod(f"big-{i}", {"app": "web"})
            pod.spec.containers[0].requests = resource_list(
                cpu="2", memory="2Gi"
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # 2 schedulable TOTAL across both rows (1 per zone), 2 stuck —
        # independent per-row budgets would have promised all 4
        assert sum(counts.values()) == 2
        assert total_unschedulable(runtime, "group-a") == 2

    def test_same_key_dual_policy_takes_the_tighter_cap(self, env):
        """Regression (r3 code review): two same-key constraints with
        different policies are BOTH enforced — the per-domain cap is
        the min over every same-key entry, so a loose Ignore entry
        can't mask a tight Honor one."""
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(
            ready_node("unmanaged", {ZONE_KEY: "us-c"})
        )
        for i in range(5):
            pod = spread_pod(f"p{i}", {"app": "web"}, max_skew=3)
            pod.spec.topology_spread_constraints[0].node_affinity_policy = (
                "Ignore"
            )
            pod.spec.topology_spread_constraints.append(
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": "web"}},
                )
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the skew-1 Honor entry caps each zone at 1 (empty zone c
        # freezes the minimum); enforcing only the sorted-first Ignore
        # skew-3 entry would have admitted all 5
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 1,
        }
        assert total_unschedulable(runtime, "group-a") == 3

    def test_differing_affinity_policies_stay_separate_entries(self):
        """Regression (r3 code review): a Honor and an Ignore constraint
        on the same (key, selector) are enforced independently by the
        scheduler — merging them could loosen the caps either enforces
        alone. They must canonicalize to two entries."""
        from karpenter_tpu.api.core import (
            TopologySpreadConstraint,
            spread_shape,
        )

        def constraint(policy):
            return TopologySpreadConstraint(
                max_skew=1,
                topology_key=ZONE_KEY,
                when_unsatisfiable="DoNotSchedule",
                label_selector={"matchLabels": {"app": "web"}},
                node_affinity_policy=policy,
            )

        shape = spread_shape(
            [constraint(""), constraint("Ignore")],
            "default",
            {"app": "web"},
        )
        entries = shape[1]
        assert len(entries) == 2
        assert {entry[5] for entry in entries} == {True, False}
        # same policy twice still merges to the most restrictive
        merged = spread_shape(
            [constraint(""), constraint("")], "default", {"app": "web"}
        )
        assert len(merged[1]) == 1

    def test_namespaces_do_not_share_counts(self, env):
        """Occupancy is namespace-scoped like the scheduler's: another
        namespace's identical pods don't skew this workload."""
        runtime, _ = env
        zoned(runtime)
        for i in range(2):
            runtime.store.create(
                bound_pod(f"old{i}", {"app": "web"}, "n-a",
                          namespace="elsewhere")
            )
        for i in range(4):
            runtime.store.create(spread_pod(f"p{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [2, 2]

    def test_all_encode_paths_agree_with_occupancy(self):
        """Oracle, pod-cache, and feed paths must emit identical
        statuses when existing pods shape the split."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import (
            PendingFeed,
            PendingPodCache,
        )
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)
        feed = PendingFeed(store, group_profile)
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"})
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        store.create(bound_pod("old", {"app": "web"}, "n-a"))
        for i in range(3):
            store.create(spread_pod(f"p{i}", {"app": "web"}))
        store.create(anti_pod("db-0"))
        store.create(bound_pod("db-live", {"app": "db"}, "n-b"))

        results = []
        for kwargs in ({}, {"pod_cache": cache}, {"feed": feed}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name: (
                        mp.status.pending_capacity.pending_pods,
                        mp.status.pending_capacity.unschedulable_pods,
                    )
                    for mp in mps
                }
            )
        assert results[0] == results[1] == results[2]
        # spread: a holds 1 -> water-fill sends 2 to b, 1 to a;
        # anti: db-live occupies zone b -> the db replica lands in a
        assert results[0]["group-a"] == (2, 0)
        assert results[0]["group-b"] == (2, 0)


class TestAntiAffinityOccupancy:
    """Occupied domains are spent; co-location pins to existing pods."""

    def test_occupied_zone_is_spent(self, env):
        """An existing replica in zone a: 3 new zone-anti replicas have
        only zones b and c left — one each, one unschedulable."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b", "c"))
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        for i in range(3):
            runtime.store.create(anti_pod(f"db-{i}"))
        runtime.manager.reconcile_all()
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 1, "group-c": 1}
        assert total_unschedulable(runtime, "group-a") == 1

    def test_statefulset_labels_still_block(self, env):
        """The existing replica carries per-pod labels (the StatefulSet
        pod-name label); it matches the workload's SELECTOR and must
        still spend its zone."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod(
                "db-0",
                {"app": "db",
                 "statefulset.kubernetes.io/pod-name": "db-0"},
                "n-a",
            )
        )
        runtime.store.create(
            anti_pod(
                "db-1",
                labels={"app": "db",
                        "statefulset.kubernetes.io/pod-name": "db-1"},
                selector_labels={"app": "db"},
            )
        )
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_foreign_pods_do_not_block(self, env):
        """Scheduled pods that don't match the workload selector leave
        its domains free."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(bound_pod("web", {"app": "web"}, "n-a"))
        for i in range(2):
            runtime.store.create(anti_pod(f"db-{i}"))
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [1, 1]
        assert total_unschedulable(runtime, "group-a") == 0

    def test_anti_split_respects_spread_zero_capacity(self, env):
        """Regression (r3 code review): a row with BOTH hard spread and
        zone anti-affinity splits by the anti rule, but a zone whose
        spread capacity is already spent (here by a foreign-selector
        constraint over existing cache pods) must never receive the
        anti replica."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b", "c"))
        for i in range(2):
            runtime.store.create(
                bound_pod(f"cache-{i}", {"tier": "cache"}, "n-a")
            )
        for i in range(2):
            pod = anti_pod(f"db-{i}")
            pod.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"tier": "cache"}},
                )
            ]
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # zone a holds 2 cache pods, skew 2 > maxSkew 1 over b/c's 0:
        # its spread capacity is zero, so the anti hand-out must use
        # zones b and c even though a is anti-free
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 1, "group-c": 1}
        assert total_unschedulable(runtime, "group-a") == 0

    def test_co_location_pins_to_existing_domain(self, env):
        """Required self-affinity with a live replica: new replicas must
        join a domain that already holds a matching pod."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b", "c"))
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-b"))
        for i in range(3):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(), co_keys=(ZONE_KEY,))
            )
        runtime.manager.reconcile_all()
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 3, "group-c": 0}

    def test_co_location_bootstrap_without_existing_pods(self, env):
        """No matching pod anywhere: the k8s first-replica special case
        — the term imposes nothing beyond one-domain co-location."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i in range(3):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(), co_keys=(ZONE_KEY,))
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [0, 3]
        assert total_unschedulable(runtime, "group-a") == 0

    def test_co_and_anti_with_existing_pods(self, env):
        """Rack anti + zone co with a live replica: new replicas join
        the live zone but must take fresh racks."""
        runtime, _ = env
        rack = "topology.kubernetes.io/rack"
        for z, r in (("a", "r1"), ("b", "r2"), ("c", "r3")):
            zone = "z1" if z in ("a", "b") else "z2"
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: zone, rack: r},
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        # live replica in zone z1 / rack r1
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        for i in range(2):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(rack,), co_keys=(ZONE_KEY,))
            )
        runtime.manager.reconcile_all()
        # zone pinned to z1 (groups a, b); rack r1 spent -> only b fits
        # one replica; the second has no rack left in z1
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 1, "group-c": 0}
        assert total_unschedulable(runtime, "group-a") == 1


def soft_spread_pod(name, labels, node_selector=None):
    """A pending pod with a ScheduleAnyway zone-spread constraint —
    scored, never constraining."""
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
            node_selector=dict(node_selector or {}),
        ),
    )
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key=ZONE_KEY,
            when_unsatisfiable="ScheduleAnyway",
            label_selector={"matchLabels": dict(labels)},
        )
    ]
    return pod


def soft_anti_pod(name, labels=None, weight=100, sign="anti"):
    """A pending pod with PREFERRED self-(anti-)affinity on the zone
    key — the spread-replicas-apart (anti) / pack-replicas-together
    (affinity) preference."""
    from karpenter_tpu.api.core import WeightedPodAffinityTerm

    labels = dict(labels or {"app": "db"})
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
        ),
    )
    term = WeightedPodAffinityTerm(
        weight=weight,
        pod_affinity_term=PodAffinityTerm(
            label_selector=LabelSelector(match_labels=dict(labels)),
            topology_key=ZONE_KEY,
        ),
    )
    pod.spec.affinity = Affinity(
        pod_anti_affinity=(
            PodAntiAffinity(
                preferred_during_scheduling_ignored_during_execution=[term]
            )
            if sign == "anti"
            else None
        ),
        pod_affinity=(
            PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[term]
            )
            if sign == "co"
            else None
        ),
    )
    return pod


class TestSoftConstraintScoring:
    """ScheduleAnyway spread and preferred self-(anti-)affinity as
    pod_group_score contributions — the kube-scheduler's scoring
    plugins, steering but never constraining."""

    def test_prefer_no_schedule_taint_steers_but_never_blocks(self, env):
        """The TaintToleration scoring plugin: a PreferNoSchedule taint
        steers intolerant pods to the untainted group; a tolerating pod
        is indifferent (index tie-break); and with ONLY the tainted
        group present the pods still schedule — a preference, never a
        constraint."""
        from karpenter_tpu.api.core import Taint, Toleration

        runtime, _ = env
        soft = Taint(key="burst", value="spot", effect="PreferNoSchedule")
        tainted = ready_node("n-a", {"group": "a"})
        tainted.spec.taints = [soft]
        runtime.store.create(tainted)
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(ready_node("n-b", {"group": "b"}))
        runtime.store.create(pending_mp("group-b", {"group": "b"}))
        for i in range(3):
            runtime.store.create(
                bound_pod(f"x{i}", {"app": "w"}, "n-a")
            )  # occupancy noise; scoring ignores it
        intolerant = Pod(
            metadata=ObjectMeta(name="plain", labels={"app": "w"}),
            spec=PodSpec(
                node_name="",
                containers=[
                    Container(requests=resource_list(cpu="1", memory="1Gi"))
                ],
            ),
        )
        tolerating = Pod(
            metadata=ObjectMeta(name="tol", labels={"app": "w"}),
            spec=PodSpec(
                node_name="",
                containers=[
                    Container(requests=resource_list(cpu="1", memory="1Gi"))
                ],
            ),
        )
        tolerating.spec.tolerations = [
            Toleration(key="burst", value="spot",
                       effect="PreferNoSchedule")
        ]
        runtime.store.create(intolerant)
        runtime.store.create(tolerating)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # intolerant steers to b; tolerating ties -> group-a (index 0)
        assert counts == {"group-a": 1, "group-b": 1}, counts
        assert total_unschedulable(runtime, "group-a") == 0

    def test_prefer_no_schedule_only_group_still_schedules(self, env):
        from karpenter_tpu.api.core import Taint

        runtime, _ = env
        tainted = ready_node("n-a", {"group": "a"})
        tainted.spec.taints = [
            Taint(key="burst", value="spot", effect="PreferNoSchedule")
        ]
        runtime.store.create(tainted)
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        for i in range(2):
            runtime.store.create(
                Pod(
                    metadata=ObjectMeta(name=f"p{i}",
                                        labels={"app": "w"}),
                    spec=PodSpec(
                        node_name="",
                        containers=[
                            Container(
                                requests=resource_list(
                                    cpu="1", memory="1Gi"
                                )
                            )
                        ],
                    ),
                )
            )
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a"]) == {"group-a": 2}
        assert total_unschedulable(runtime, "group-a") == 0

    def test_schedule_anyway_steers_to_emptier_domain(self, env):
        runtime, _ = env
        zoned(runtime)
        for i in range(2):
            runtime.store.create(
                bound_pod(f"old{i}", {"app": "web"}, "n-a")
            )
        for i in range(4):
            runtime.store.create(soft_spread_pod(f"p{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        # a preference steers the whole shape to the emptier zone; it
        # must never mark anything unschedulable
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 4,
        }
        assert total_unschedulable(runtime, "group-a") == 0

    def test_schedule_anyway_never_blocks(self, env):
        """Only the loaded zone is feasible: ScheduleAnyway yields."""
        runtime, _ = env
        zoned(runtime, zones=("a",))
        runtime.store.create(bound_pod("old", {"app": "web"}, "n-a"))
        for i in range(3):
            runtime.store.create(soft_spread_pod(f"p{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a"]) == {"group-a": 3}
        assert total_unschedulable(runtime, "group-a") == 0

    def test_schedule_anyway_ranks_keyless_groups_last(self, env):
        runtime, _ = env
        # group-a has NO zone label; group-b is keyed and empty
        runtime.store.create(ready_node("n-a", {"group": "a"}))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(
            ready_node("n-b", {"group": "b", ZONE_KEY: "us-b"})
        )
        runtime.store.create(pending_mp("group-b", {"group": "b"}))
        for i in range(2):
            runtime.store.create(soft_spread_pod(f"p{i}", {"app": "web"}))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 2,
        }

    def test_preferred_anti_avoids_occupied_zone(self, env):
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        for i in range(2):
            runtime.store.create(soft_anti_pod(f"db-{i}"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 2,
        }
        assert total_unschedulable(runtime, "group-a") == 0

    def test_preferred_anti_yields_when_only_occupied_zone_fits(self, env):
        runtime, _ = env
        zoned(runtime, zones=("a",))
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        runtime.store.create(soft_anti_pod("db-1"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a"]) == {"group-a": 1}
        assert total_unschedulable(runtime, "group-a") == 0

    def test_preferred_co_packs_toward_existing_replicas(self, env):
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-b"))
        for i in range(2):
            runtime.store.create(soft_anti_pod(f"db-{i}", sign="co"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 2,
        }

    def test_foreign_selector_preference_is_not_modeled(self, env):
        """A preferred anti term over ANOTHER workload's labels is not
        self-matching: decoded, no score contribution — the row stays
        on plain first-feasible assignment."""
        runtime, _ = env
        zoned(runtime)
        runtime.store.create(bound_pod("web", {"app": "web"}, "n-a"))
        pod = soft_anti_pod("db-1", labels={"app": "db"})
        term = (
            pod.spec.affinity.pod_anti_affinity
            .preferred_during_scheduling_ignored_during_execution[0]
        )
        term.pod_affinity_term.label_selector = LabelSelector(
            match_labels={"app": "web"}
        )
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # first feasible group wins (group-a), despite web's presence
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 0,
        }

    def test_all_encode_paths_agree_with_soft_scoring(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import (
            PendingFeed,
            PendingPodCache,
        )
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)
        feed = PendingFeed(store, group_profile)
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"})
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        store.create(bound_pod("old", {"app": "web"}, "n-a"))
        for i in range(3):
            store.create(soft_spread_pod(f"p{i}", {"app": "web"}))
        store.create(soft_anti_pod("db-1"))
        results = []
        for kwargs in ({}, {"pod_cache": cache}, {"feed": feed}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name: (
                        mp.status.pending_capacity.pending_pods,
                        mp.status.pending_capacity.unschedulable_pods,
                    )
                    for mp in mps
                }
            )
        assert results[0] == results[1] == results[2]
        # web steers to the emptier zone b; db has no occupancy signal
        # and stays first-feasible (a)
        assert results[0]["group-b"][0] == 3


def foreign_pod(name, sign="anti", key=ZONE_KEY, selector=None,
                namespaces=()):
    """A pending pod with a required (anti-)affinity term whose selector
    matches ANOTHER workload's pods (app=redis), not its own."""
    pod = Pod(
        metadata=ObjectMeta(name=name, labels={"app": "web"}),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu="1", memory="1Gi"))
            ],
        ),
    )
    term = PodAffinityTerm(
        label_selector=LabelSelector(
            match_labels=dict(selector or {"app": "redis"})
        ),
        topology_key=key,
        namespaces=list(namespaces),
    )
    pod.spec.affinity = Affinity(
        pod_anti_affinity=(
            PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[term]
            )
            if sign == "anti"
            else None
        ),
        pod_affinity=(
            PodAffinity(
                required_during_scheduling_ignored_during_execution=[term]
            )
            if sign == "co"
            else None
        ),
    )
    return pod


class TestForeignAffinityOccupancy:
    """Required (anti-)affinity against OTHER workloads' pods, enforced
    against SCHEDULED state through the census (the pending-vs-pending
    interaction stays out of scope, docs/OPERATIONS.md)."""

    def test_foreign_anti_blocks_occupied_domains(self, env):
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(bound_pod("redis", {"app": "redis"}, "n-a"))
        for i in range(3):
            runtime.store.create(foreign_pod(f"web-{i}"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 3,
        }
        assert total_unschedulable(runtime, "group-a") == 0

    def test_foreign_anti_without_matching_pods_is_free(self, env):
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i in range(2):
            runtime.store.create(foreign_pod(f"web-{i}"))
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 2
        assert total_unschedulable(runtime, "group-a") == 0

    def test_foreign_co_requires_an_occupied_domain(self, env):
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(bound_pod("redis", {"app": "redis"}, "n-b"))
        for i in range(2):
            runtime.store.create(foreign_pod(f"web-{i}", sign="co"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 2,
        }

    def test_foreign_co_without_matching_pods_is_unschedulable(self, env):
        """No first-replica bootstrap for a foreign selector: if no
        matching pod exists anywhere, the scheduler will never admit
        the pod — the signal must not size a scale-up for it."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(foreign_pod("web-0", sign="co"))
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 0
        assert total_unschedulable(runtime, "group-a") == 1

    def test_foreign_namespaces_scope_the_census(self, env):
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod("redis", {"app": "redis"}, "n-a",
                      namespace="other")
        )
        # the term scopes to namespace "other": the redis there blocks
        runtime.store.create(
            foreign_pod("web-0", namespaces=("other",))
        )
        # an unscoped term sees only the pod's OWN namespace: free
        runtime.store.create(foreign_pod("web-1"))
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 1,
        }

    def test_self_anti_with_extra_namespaces_blocks_there_too(self, env):
        """Regression (r3 code review): a SELF-matching anti term whose
        namespaces list spans the own namespace plus others must also
        block on matching pods in those other namespaces — the self
        machinery only censuses the own one."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod("twin", {"app": "db"}, "n-a", namespace="staging")
        )
        pod = anti_pod("db-0")
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespaces = ["default", "staging"]
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the staging twin occupies zone a: the replica must land in b
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_self_co_with_extra_namespaces_pins_to_their_domains(self, env):
        """Regression (r3 advisor, low): a SELF-matching required
        co-location term whose namespaces list spans the own namespace
        plus others — matching pods in THOSE namespaces pin the
        scheduler to their domains even when the own namespace holds no
        match. The pre-fix model granted first-replica bootstrap and
        promised placement anywhere."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod("twin", {"app": "db"}, "n-b", namespace="staging")
        )
        pod = anti_pod("db-0", keys=(), co_keys=(ZONE_KEY,))
        term = (
            pod.spec.affinity.pod_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespaces = ["default", "staging"]
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the staging twin occupies zone b: the replica is pinned there
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_self_co_with_extra_namespaces_keeps_bootstrap(self, env):
        """The +2 projection keeps the scheduler's first-replica grace:
        with NO matching pod in ANY in-scope namespace the term imposes
        nothing (the pod itself is in scope and matches), unlike a true
        foreign co term."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        pod = anti_pod("db-0", keys=(), co_keys=(ZONE_KEY,))
        term = (
            pod.spec.affinity.pod_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespaces = ["default", "staging"]
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 1
        assert total_unschedulable(runtime, "group-a") == 0

    def test_self_co_hostname_with_extra_namespaces_is_honest(self, env):
        """A hostname-keyed self co term with extra namespaces: a
        matching pod in a foreign in-scope namespace pins the pod to an
        EXISTING node, which fresh nodes can never satisfy — the row is
        honestly unschedulable; with no matching pod anywhere the
        first-replica grace applies (r4 code review)."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod("twin", {"app": "db"}, "n-b", namespace="staging")
        )
        pod = anti_pod("db-0", keys=(), co_keys=("kubernetes.io/hostname",))
        term = (
            pod.spec.affinity.pod_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespaces = ["default", "staging"]
        runtime.store.create(pod)
        # grace case: no matching pod in scope for this second workload
        pod2 = anti_pod(
            "web-0", labels={"app": "web"}, keys=(),
            co_keys=("kubernetes.io/hostname",),
        )
        term2 = (
            pod2.spec.affinity.pod_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term2.namespaces = ["default", "staging"]
        runtime.store.create(pod2)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        # db-0 pinned to the staging twin's node: unschedulable on any
        # scale-up; web-0 bootstraps freely
        assert sum(counts.values()) == 1
        assert total_unschedulable(runtime, "group-a") == 1

    def test_hostname_self_co_pins_to_existing_node(self, env):
        """Required self co-location on kubernetes.io/hostname with a
        matching scheduled pod: new replicas must join its EXISTING
        node, which no scale-up's fresh node can satisfy — honestly
        unschedulable (was silently unconstrained before r4)."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        for i in range(2):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(),
                         co_keys=("kubernetes.io/hostname",))
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 0
        assert total_unschedulable(runtime, "group-a") == 2

    def test_hostname_self_co_bootstrap_promises_one(self, env):
        """With NO matching pod anywhere, the first replica bootstraps
        onto a fresh node — but replicas beyond the first must join
        ITS node, which a group-level pack cannot promise: exactly one
        replica is promised, the rest honestly unschedulable."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i in range(3):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(),
                         co_keys=("kubernetes.io/hostname",))
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 1
        assert total_unschedulable(runtime, "group-a") == 2

    def test_hostname_self_co_multi_row_promises_one_total(self, env):
        """A hostname-co workload split across request-distinct rows
        (mid-VPA rollout): the single bootstrap promise is handed to
        the canonically-first row — one replica total, never one per
        row."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i, cpu in enumerate(("1", "2", "2", "1")):
            pod = anti_pod(f"db-{i}", keys=(),
                           co_keys=("kubernetes.io/hostname",))
            pod.spec.containers[0].requests = resource_list(
                cpu=cpu, memory="1Gi"
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 1
        assert total_unschedulable(runtime, "group-a") == 3

    def test_hostname_self_co_with_zone_anti_promises_one(self, env):
        """Required zone ANTI-affinity (one per zone) combined with
        required hostname CO-location (all on one node) is contradictory
        beyond a single replica: the per-domain hand-out is truncated to
        ONE promise total, never one per anti domain."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for i in range(3):
            runtime.store.create(
                anti_pod(f"db-{i}", keys=(ZONE_KEY,),
                         co_keys=("kubernetes.io/hostname",))
            )
        runtime.manager.reconcile_all()
        counts = pods_per_group(runtime, ["group-a", "group-b"])
        assert sum(counts.values()) == 1
        assert total_unschedulable(runtime, "group-a") == 2

    def test_none_namespaces_field_is_tolerated(self):
        """namespaces: null hydrates to None — the shape build must not
        crash (r3 code review)."""
        from karpenter_tpu.api.core import pod_affinity_shape

        pod = foreign_pod("web-0")
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespaces = None
        shape = pod_affinity_shape(
            pod.spec.affinity, pod.metadata.labels, "default"
        )
        assert shape[4] == (
            (-1, ZONE_KEY, ((("app", "redis"),), ()),
             ("names", ("default",))),
        )

    def test_match_label_keys_make_per_revision_anti_groups(self, env):
        """podAffinityTerm.matchLabelKeys (k8s >= 1.29): the incoming
        pod's values refine the selector, so two revisions of one app
        form SEPARATE anti-groups — v1's zone doesn't block v2."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod(
                "v1-0",
                {"app": "db", "pod-template-hash": "v1"},
                "n-a",
            )
        )
        pod = anti_pod(
            "v2-0",
            labels={"app": "db", "pod-template-hash": "v2"},
            selector_labels={"app": "db"},
        )
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.match_label_keys = ["pod-template-hash"]
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the v1 replica in zone a does NOT match the refined selector
        # (hash=v2): zone a stays open and first-feasible wins
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 1,
            "group-b": 0,
        }

    def test_mismatch_label_keys_turn_self_terms_foreign(self, env):
        """mismatchLabelKeys excludes the pod's own value: the term can
        only match OTHER revisions — enforced as a foreign term against
        their scheduled replicas."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod(
                "v1-0",
                {"app": "db", "pod-template-hash": "v1"},
                "n-a",
            )
        )
        pod = anti_pod(
            "v2-0",
            labels={"app": "db", "pod-template-hash": "v2"},
            selector_labels={"app": "db"},
        )
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.mismatch_label_keys = ["pod-template-hash"]
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the refined selector (app=db AND hash NotIn [v2]) matches the
        # v1 replica: its zone a is forbidden
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_namespace_selector_resolves_against_labels(self, env):
        """A namespaceSelector term censuses every namespace whose
        labels match — the Namespace mirror closes the last decode-only
        slice."""
        from karpenter_tpu.api.core import Namespace

        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            Namespace(metadata=ObjectMeta(
                name="data", namespace="", labels={"team": "data"}))
        )
        runtime.store.create(
            Namespace(metadata=ObjectMeta(
                name="web", namespace="", labels={"team": "web"}))
        )
        runtime.store.create(
            bound_pod("redis", {"app": "redis"}, "n-a", namespace="data")
        )
        pod = foreign_pod("app-0")
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespace_selector = LabelSelector(
            match_labels={"team": "data"}
        )
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # the data namespace's redis occupies zone a: blocked there
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_empty_namespace_selector_means_all_namespaces(self, env):
        from karpenter_tpu.api.core import Namespace

        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            Namespace(metadata=ObjectMeta(name="anywhere", namespace=""))
        )
        runtime.store.create(
            bound_pod("redis", {"app": "redis"}, "n-a",
                      namespace="anywhere")
        )
        pod = foreign_pod("app-0")
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespace_selector = LabelSelector()  # {} = every namespace
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_self_anti_with_namespace_selector_stays_one_per_domain(
        self, env
    ):
        """A namespaceSelector anti term whose selector matches the
        pod's OWN labels keeps the self 1-per-domain rule (conservative:
        whether the own namespace matches can't be known at shape
        build) AND blocks on matching pods in selector-matching
        namespaces."""
        from karpenter_tpu.api.core import Namespace

        runtime, _ = env
        zoned(runtime, zones=("a", "b", "c"))
        runtime.store.create(
            Namespace(metadata=ObjectMeta(
                name="prod", namespace="", labels={"env": "prod"}))
        )
        runtime.store.create(
            bound_pod("db-live", {"app": "db"}, "n-a", namespace="prod")
        )
        for i in range(3):
            pod = anti_pod(f"db-{i}")
            term = (
                pod.spec.affinity.pod_anti_affinity
                .required_during_scheduling_ignored_during_execution[0]
            )
            term.namespace_selector = LabelSelector(
                match_labels={"env": "prod"}
            )
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # zone a blocked by prod's replica; the three pending replicas
        # still spread one-per-domain over b and c: one unschedulable
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 1, "group-c": 1}
        assert total_unschedulable(runtime, "group-a") == 1

    def test_namespace_selector_unions_with_explicit_list(self, env):
        """The k8s combination rule: namespaces + namespaceSelector is
        the UNION of both scopes."""
        from karpenter_tpu.api.core import Namespace

        runtime, _ = env
        zoned(runtime, zones=("a", "b", "c"))
        runtime.store.create(
            Namespace(metadata=ObjectMeta(
                name="data", namespace="", labels={"team": "data"}))
        )
        runtime.store.create(
            bound_pod("redis-1", {"app": "redis"}, "n-a",
                      namespace="data")
        )
        runtime.store.create(
            bound_pod("redis-2", {"app": "redis"}, "n-b",
                      namespace="legacy")
        )
        pod = foreign_pod("app-0", namespaces=("legacy",))
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespace_selector = LabelSelector(
            match_labels={"team": "data"}
        )
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # both scopes block: data's redis in zone a, legacy's in zone b
        assert pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        ) == {"group-a": 0, "group-b": 0, "group-c": 1}

    def test_co_with_namespace_selector_requires_matching_ns(self, env):
        from karpenter_tpu.api.core import Namespace

        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            Namespace(metadata=ObjectMeta(
                name="data", namespace="", labels={"team": "data"}))
        )
        runtime.store.create(
            bound_pod("redis", {"app": "redis"}, "n-b", namespace="data")
        )
        pod = foreign_pod("app-0", sign="co")
        term = (
            pod.spec.affinity.pod_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespace_selector = LabelSelector(
            match_labels={"team": "data"}
        )
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        # must join data's redis zone
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_anti_selector_falls_back_without_namespace_objects(
        self, env
    ):
        """Regression (r3 code review): with NO Namespace objects to
        resolve against (fixtures, simulations), an anti
        namespaceSelector must block conservatively against every
        namespace the occupancy knows — silent non-enforcement would
        over-promise."""
        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        runtime.store.create(
            bound_pod("redis", {"app": "redis"}, "n-a", namespace="data")
        )
        pod = foreign_pod("app-0")
        term = (
            pod.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution[0]
        )
        term.namespace_selector = LabelSelector(
            match_labels={"team": "data"}
        )
        runtime.store.create(pod)
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a", "group-b"]) == {
            "group-a": 0,
            "group-b": 1,
        }

    def test_foreign_hostname_co_is_unschedulable(self, env):
        """'Must share a NODE with an existing pod' can never be met by
        a scale-up's fresh nodes."""
        runtime, _ = env
        zoned(runtime, zones=("a",))
        runtime.store.create(bound_pod("redis", {"app": "redis"}, "n-a"))
        runtime.store.create(
            foreign_pod("web-0", sign="co",
                        key="kubernetes.io/hostname")
        )
        runtime.manager.reconcile_all()
        assert pods_per_group(runtime, ["group-a"]) == {"group-a": 0}
        assert total_unschedulable(runtime, "group-a") == 1


class TestEncodeMemoWithOccupancy:
    """Bound-pod churn must not thrash the encode memo of fleets without
    spread/anti constraints — and must invalidate it for fleets with."""

    def _solve(self, store, feed, counter):
        from karpenter_tpu.metrics.producers import pendingcapacity as PC
        from karpenter_tpu.metrics.registry import GaugeRegistry

        mps = [
            mp for mp in store.list("MetricsProducer")
            if mp.spec.pending_capacity is not None
        ]
        PC.solve_pending(store, mps, GaugeRegistry(), feed=feed)
        return counter[0]

    @pytest.fixture
    def counting_encode(self, monkeypatch):
        from karpenter_tpu.metrics.producers import pendingcapacity as PC

        counter = [0]
        real = PC.encode_snapshot

        def counting(*args, **kwargs):
            counter[0] += 1
            return real(*args, **kwargs)

        monkeypatch.setattr(PC, "encode_snapshot", counting)
        return counter

    def test_unconstrained_fleet_ignores_bound_churn(self, counting_encode):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed
        from karpenter_tpu.store.store import Store

        store = Store()
        feed = PendingFeed(store, group_profile)
        store.create(ready_node("n1", {"group": "a"}))
        store.create(pending_mp("group-a", {"group": "a"}))
        store.create(
            Pod(metadata=ObjectMeta(name="p0"),
                spec=PodSpec(
                    node_name="",
                    containers=[Container(
                        requests=resource_list(cpu="1", memory="1Gi"))],
                ))
        )
        assert self._solve(store, feed, counting_encode) == 1
        store.create(bound_pod("scheduled", {"app": "web"}, "n1"))
        assert self._solve(store, feed, counting_encode) == 1  # memo hit

    def test_census_refresh_counter_published(self):
        from karpenter_tpu.metrics.producers import pendingcapacity as PC
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingFeed
        from karpenter_tpu.store.store import Store

        store = Store()
        feed = PendingFeed(store, group_profile)
        registry = GaugeRegistry()
        store.create(ready_node("n1", {"group": "a", ZONE_KEY: "us-a"}))
        store.create(pending_mp("group-a", {"group": "a"}))
        store.create(spread_pod("p0", {"app": "web"}))

        def solve():
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            PC.solve_pending(store, mps, registry, feed=feed)

        solve()
        counter = registry.register(
            "runtime", "census_refresh_total", kind="counter"
        )
        first = counter.get("-", "-") or 0
        assert first >= 1  # the first constrained solve recomputed
        solve()  # nothing churned: served from the census memo
        assert (counter.get("-", "-") or 0) == first
        store.create(bound_pod("scheduled", {"app": "web"}, "n1"))
        solve()
        assert (counter.get("-", "-") or 0) == first + 1

    def test_constrained_fleet_reencodes_on_bound_churn(
        self, counting_encode
    ):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed
        from karpenter_tpu.store.store import Store

        store = Store()
        feed = PendingFeed(store, group_profile)
        store.create(ready_node("n1", {"group": "a", ZONE_KEY: "us-a"}))
        store.create(pending_mp("group-a", {"group": "a"}))
        store.create(spread_pod("p0", {"app": "web"}))
        assert self._solve(store, feed, counting_encode) == 1
        store.create(bound_pod("scheduled", {"app": "web"}, "n1"))
        # the mask inputs depend on occupancy now: must re-encode
        assert self._solve(store, feed, counting_encode) == 2


class TestLongRunBoundedState:
    @pytest.mark.skipif(
        not __import__("os").environ.get("KARPENTER_SCALE_TESTS"),
        reason="soak loop; battletest sets KARPENTER_SCALE_TESTS=1",
    )
    def test_churning_workloads_keep_caches_bounded(self, env):
        """Soak: 300 ticks of constrained workloads appearing, binding,
        and vanishing (enough churn to cross the compaction floor).
        Every watch-maintained structure must track the LIVE state, not
        the history: pending arena and shape registries compact, census
        groups drain, views stay under the cap."""
        from karpenter_tpu.store.columnar import ScheduledOccupancy

        runtime, _ = env
        zoned(runtime, zones=("a", "b"))
        for tick in range(300):
            workload = f"w{tick}"
            for i in range(4):
                runtime.store.create(
                    spread_pod(f"{workload}-p{i}", {"app": workload})
                )
            runtime.store.create(
                bound_pod(f"{workload}-live", {"app": workload}, "n-a")
            )
            runtime.clock.advance(6)
            runtime.manager.reconcile_all()
            # the previous workload schedules and vanishes entirely
            if tick:
                old = f"w{tick - 1}"
                for i in range(4):
                    runtime.store.delete("Pod", "default", f"{old}-p{i}")
                runtime.store.delete("Pod", "default", f"{old}-live")
        feed = runtime.producer_factory._pending_feed
        occupancy = feed.occupancy
        with occupancy.view() as (_, spaces):
            live_groups = sum(len(g) for g in spaces.values())
        assert live_groups <= 2  # only the newest workload's pods
        # one view per distinct selector ever queried, still under the
        # cap here — no spurious per-tick registrations (cap ENFORCEMENT
        # is exercised by test_view_cap_evicts_lru_and_counts, which
        # crosses it)
        assert len(occupancy._views) <= 301
        assert occupancy.view_evictions == 0
        assert ScheduledOccupancy.VIEW_CAP >= 301  # soak stays below
        # pending arena compacted: slot peak tracks the handful of live
        # pods plus growth since the last compaction, not the 1500
        # churned through
        assert feed.pods._hi < 600
        snap = feed.pods.snapshot()
        # registry compaction dropped the dead workloads' shapes
        assert len(snap.spread_shapes) < 100


class TestSimulateWithOccupancy:
    def test_what_if_zone_relieves_spread_pressure(self):
        """A hypothetical group in a FRESH zone becomes an eligible
        domain with zero occupancy: the water-fill routes the overflow
        there, and the delta report shows the unschedulable pods it
        absorbs."""
        from karpenter_tpu.simulate import simulate_delta
        from karpenter_tpu.store.store import Store

        store = Store()
        store.create(
            ready_node("n-a", {"group": "a", ZONE_KEY: "us-a"})
        )
        store.create(pending_mp("group-a", {"group": "a"}))
        # an empty unmanaged zone freezes the minimum: one zone-a slot
        store.create(ready_node("unmanaged", {ZONE_KEY: "us-b"}))
        for i in range(3):
            store.create(spread_pod(f"p{i}", {"app": "web"}))
        report = simulate_delta(
            store,
            [
                {
                    "name": "what-if-b",
                    "allocatable": {"cpu": "64", "memory": "64Gi"},
                    "labels": {ZONE_KEY: "us-b"},
                }
            ],
        )
        base = report["baseline"]["groups"]["default/group-a"]
        assert base["pending_pods"] == 1  # frozen minimum caps zone a
        assert report["baseline"]["unschedulable_pods"] == 2
        # the hypothetical zone-b group fills the frozen zone itself:
        # every pod schedules — and the what-if group absorbs ONLY the
        # overflow no real group can take (the no-steal invariant)
        assert report["what_if"]["unschedulable_pods"] == 0
        assert report["delta"]["unschedulable_pods"] == -2
        groups = report["what_if"]["groups"]
        assert groups["default/group-a"]["pending_pods"] == 2
        assert groups["what-if-b"]["pending_pods"] == 1

    def test_simulation_respects_existing_replicas(self):
        """The dry-run solve sees the same census the production tick
        does: an occupied zone never receives the simulated replica."""
        from karpenter_tpu.simulate import simulate
        from karpenter_tpu.store.store import Store

        store = Store()
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"})
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        store.create(bound_pod("db-live", {"app": "db"}, "n-a"))
        store.create(anti_pod("db-1"))
        report = simulate(store)
        assert report["groups"]["default/group-a"]["pending_pods"] == 0
        assert report["groups"]["default/group-b"]["pending_pods"] == 1
        assert report["unschedulable_pods"] == 0
