"""Solver introspection plane (observability/devicetelemetry.py).

The acceptance pins (ISSUE 15 / docs/observability.md "Device
telemetry & introspection"):

  * the compile ledger records every compile-cache miss — family,
    bucket rung, shard extents, wall compile seconds, the trace ids
    that paid for it — and captures XLA cost attribution (flops/bytes)
    per cache entry, which subsequent dispatch spans carry;
  * `--introspect` off (the default posture) yields BYTE-IDENTICAL
    decisions and a mark-free hot path (records_total stays 0) — the
    same property the tracing-off and provenance-off pins established;
  * STEADY-STATE COMPILE GUARD: past warm-up, the churn world records
    ZERO new ledger entries — pinning the jit-cache-key discipline the
    repo keeps re-fixing (PR 13 "signature cache stays logarithmic");
  * seeded chaos: a forced compile storm (reset_caches mid-run) trips
    exactly ONE `compile_storm` flight-recorder dump, the self-SLO
    device-memory source stays quiet, and the steady-state guard is
    green again after re-warm-up;
  * device memory telemetry publishes karpenter_device_* and the
    per-entry resident-LRU byte accounting, retires evicted entries'
    series, and feeds the self-SLO monitor as its fourth source;
  * /debug/solver reports the full posture in one JSON document;
  * overhead stays bounded (the structural guard; `make
    bench-introspect` publishes the honest <=2% number).
"""

import json
import urllib.request

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import (
    MetricsServer,
    SelfSLOMonitor,
    SolverIntrospection,
)
from karpenter_tpu.observability.devicetelemetry import CompileLedger
from karpenter_tpu.observability.flightrecorder import (
    DUMP_KINDS,
    FlightRecorder,
    default_flight_recorder,
    reset_default_flight_recorder,
    set_default_flight_recorder,
)
from karpenter_tpu.ops.binpack import BinPackInputs
from karpenter_tpu.solver.service import SolverService


def _binpack_inputs(pods=5, groups=3, seed=0):
    rng = np.random.default_rng(seed)
    return BinPackInputs(
        pod_requests=rng.uniform(
            0.5, 2.0, (pods, 2)
        ).astype(np.float32),
        pod_valid=np.ones(pods, bool),
        pod_intolerant=np.zeros((pods, 1), bool),
        pod_required=np.zeros((pods, 1), bool),
        group_allocatable=np.full((groups, 2), 8.0, np.float32),
        group_taints=np.zeros((groups, 1), bool),
        group_labels=np.zeros((groups, 1), bool),
        pod_weight=np.ones(pods, np.int32),
    )


@pytest.fixture
def fresh_recorder():
    saved = default_flight_recorder()
    recorder = reset_default_flight_recorder()
    yield recorder
    set_default_flight_recorder(saved)


class TestCompileLedger:
    def test_records_and_tail_order(self):
        ledger = CompileLedger(capacity=8)
        for i in range(3):
            ledger.record(
                family="solve", rung=f"r{i}", seconds=0.1 * (i + 1),
                trace_ids=[f"t{i}"], flops=float(i),
            )
        rows = ledger.tail()
        assert [r["rung"] for r in rows] == ["r0", "r1", "r2"]
        assert [r["seq"] for r in rows] == [1, 2, 3]
        assert rows[0]["trace_ids"] == ["t0"]
        assert rows[2]["flops"] == 2.0
        assert ledger.records_total == 3
        assert ledger.by_family == {"solve": 3}
        assert len(ledger.tail(limit=2)) == 2
        assert ledger.tail(limit=0) == []

    def test_ring_bounds(self):
        ledger = CompileLedger(capacity=4)
        for i in range(10):
            ledger.record(family="f", rung=f"r{i}", seconds=0.0)
        rows = ledger.tail()
        assert len(rows) == 4
        assert [r["rung"] for r in rows] == ["r6", "r7", "r8", "r9"]
        assert ledger.records_total == 10

    def test_extents_and_attribution_columns(self):
        ledger = CompileLedger(capacity=4)
        ledger.record(
            family="solve", rung="r", seconds=1.0, extents=(4, 2),
            flops=10.0, bytes_accessed=20.0,
        )
        row = ledger.tail()[0]
        assert row["extents"] == (4, 2)
        assert row["bytes_accessed"] == 20.0


class TestServiceCompileLedger:
    """The ledger riding real SolverService dispatches."""

    def _service(self, recorder=None, **kw):
        registry = GaugeRegistry()
        service = SolverService(registry=registry, backend="xla")
        plane = SolverIntrospection(
            enabled=True, registry=registry,
            recorder=recorder or FlightRecorder(),
            **kw,
        ).attach(service)
        return service, plane, registry

    def test_miss_recorded_with_cost_attribution(self):
        service, plane, registry = self._service()
        try:
            service.solve(_binpack_inputs())
            assert plane.ledger.records_total == 1
            row = plane.ledger.tail()[0]
            assert row["family"] == "solve"
            assert row["seconds"] > 0
            assert "xla" in row["rung"]
            # jax 0.4.37 reports analytical flops/bytes at lowering;
            # the columns exist and are populated on this backend
            assert row["flops"] is not None and row["flops"] > 0
            assert row["bytes_accessed"] is not None
            # a second identical solve HITS the cache: no new row
            service.solve(_binpack_inputs(seed=1))
            assert plane.ledger.records_total == 1
            # the histogram family landed
            hist = registry.gauge("solver", "compile_seconds")
            assert hist.count("solve", "-") == 1
        finally:
            service.close()

    def test_forecast_family_recorded(self):
        from karpenter_tpu.forecast.models import ForecastInputs

        service, plane, _ = self._service()
        try:
            S, T = 3, 16
            values = np.tile(np.arange(T, dtype=np.float32), (S, 1))
            inputs = ForecastInputs(
                values=values,
                valid=np.ones((S, T), bool),
                times=np.tile(
                    np.arange(-T + 1, 1, dtype=np.float32) * 10.0,
                    (S, 1),
                ),
                weights=np.ones((S, T), np.float32),
                horizon=np.full(S, 30.0, np.float32),
                step_s=np.full(S, 10.0, np.float32),
                model=np.zeros(S, np.int32),
                season=np.full(S, 4, np.int32),
                alpha=np.full(S, 0.5, np.float32),
                beta=np.full(S, 0.1, np.float32),
                gamma=np.full(S, 0.1, np.float32),
            )
            service.forecast(inputs)
            assert plane.ledger.by_family.get("forecast") == 1
        finally:
            service.close()

    def test_disabled_plane_is_mark_free(self):
        service, plane, _ = self._service()
        plane.enabled = False
        try:
            service.solve(_binpack_inputs())
            service.solve(_binpack_inputs(seed=1))
            assert plane.ledger.records_total == 0
            assert plane.ledger.tail() == []
            plane.on_tick()
            assert plane.storms_total == 0
        finally:
            service.close()

    def test_dispatch_spans_gain_cost_args(self):
        from karpenter_tpu.observability import (
            default_tracer,
            reset_default_tracer,
            set_default_tracer,
        )

        saved = default_tracer()
        tracer = reset_default_tracer()
        service, plane, _ = self._service()
        try:
            with tracer.trace("tick"):
                service.solve(_binpack_inputs())
            with tracer.trace("tick"):
                service.solve(_binpack_inputs(seed=1))
            spans = [
                s for s in tracer.snapshot()
                if s["name"] == "solver.dispatch"
            ]
            assert len(spans) == 2
            # attribution is captured at compile time (first dispatch),
            # so the SECOND dispatch's span carries it
            assert "flops" in spans[1]["args"]
            assert spans[1]["args"]["flops"] > 0
            assert "bytes" in spans[1]["args"]
            # and the ledger row backlinks the paying trace
            assert plane.ledger.tail()[0]["trace_ids"]
        finally:
            service.close()
            set_default_tracer(saved)


class TestCompileStormDetector:
    def _plane(self, recorder, threshold=2):
        registry = GaugeRegistry()
        return SolverIntrospection(
            enabled=True, registry=registry, recorder=recorder,
            storm_threshold=threshold,
        ), registry

    def test_cold_boot_taper_never_trips(self):
        recorder = FlightRecorder()
        plane, _ = self._plane(recorder)
        # boot: misses taper 3 -> 1 -> 0; the detector is not yet
        # armed, so no storm fires even above threshold
        for n in (3, 1):
            for _ in range(n):
                plane.ledger.record(family="solve", rung="r", seconds=0.1)
            plane.on_tick()
        assert plane.storms_total == 0
        plane.on_tick()  # zero-miss tick: armed
        assert plane.storms_total == 0

    def test_steady_state_burst_trips_once_with_hysteresis(self):
        recorder = FlightRecorder()
        plane, registry = self._plane(recorder)
        plane.on_tick()  # zero-miss tick arms the detector
        for i in range(3):
            plane.ledger.record(
                family="solve", rung=f"r{i}", seconds=0.1,
                trace_ids=[f"t{i}"],
            )
        plane.on_tick()
        assert plane.storms_total == 1
        events = recorder.events(kind="compile_storm")
        assert len(events) == 1
        assert events[0]["misses"] == 3
        assert events[0]["families"] == ["solve"]
        assert set(events[0]["trace_ids"]) == {"t0", "t1", "t2"}
        assert "compile_storm" in DUMP_KINDS
        # continued misses in the SAME incident do not re-trip
        plane.ledger.record(family="solve", rung="r9", seconds=0.1)
        plane.ledger.record(family="solve", rung="r10", seconds=0.1)
        plane.on_tick()
        assert plane.storms_total == 1
        # a zero-miss tick re-arms; the next burst is a new incident
        plane.on_tick()
        plane.ledger.record(family="solve", rung="r11", seconds=0.1)
        plane.ledger.record(family="solve", rung="r12", seconds=0.1)
        plane.on_tick()
        assert plane.storms_total == 2
        counter = registry.gauge("solver", "compile_storms_total")
        assert counter.get("-", "-") == 2.0

    def test_below_threshold_misses_do_not_trip(self):
        plane, _ = self._plane(FlightRecorder(), threshold=3)
        plane.on_tick()
        plane.ledger.record(family="solve", rung="r", seconds=0.1)
        plane.on_tick()
        assert plane.storms_total == 0


class TestDeviceMemoryTelemetry:
    def test_gauges_and_watermark(self):
        registry = GaugeRegistry()
        stats = [{
            "device": "tpu:0",
            "bytes_in_use": 950,
            "bytes_limit": 1000,
        }]
        plane = SolverIntrospection(
            enabled=True, registry=registry,
            recorder=FlightRecorder(),
            stats_source=lambda: stats,
            watermark=0.9,
        )
        plane.on_tick()
        in_use = registry.gauge("device", "bytes_in_use")
        limit = registry.gauge("device", "bytes_limit")
        assert in_use.get("tpu:0", "-") == 950.0
        assert limit.get("tpu:0", "-") == 1000.0
        assert plane.memory_source() is True
        stats[0]["bytes_in_use"] = 100
        plane.on_tick()
        assert plane.memory_source() is False

    def test_no_stats_backend_is_quiet(self):
        plane = SolverIntrospection(
            enabled=True, registry=GaugeRegistry(),
            recorder=FlightRecorder(), stats_source=lambda: [],
        )
        plane.on_tick()
        assert plane.memory_source() is None

    def test_disabled_plane_memory_source_is_none(self):
        plane = SolverIntrospection(
            enabled=False,
            stats_source=lambda: [{
                "device": "d", "bytes_in_use": 99, "bytes_limit": 100,
            }],
        )
        plane.on_tick()
        assert plane.memory_source() is None

    def test_selfslo_counts_memory_events(self):
        high = {"value": True}
        monitor = SelfSLOMonitor(
            registry=GaugeRegistry(),
            memory_source=lambda: high["value"],
        )
        report = monitor.evaluate(now=1000.0)
        assert report["windows"]["5m"]["bad"] == 1
        high["value"] = False
        report = monitor.evaluate(now=1010.0)
        assert report["windows"]["5m"]["total"] == 2
        assert report["windows"]["5m"]["bad"] == 1
        board = monitor.scoreboard()
        assert board["device_memory"] == "ok"
        high["value"] = None
        report = monitor.evaluate(now=1020.0)
        # None contributes NO event — the quiet contract
        assert report["windows"]["5m"]["total"] == 2
        assert monitor.scoreboard()["device_memory"] == "off"

    def test_resident_entry_gauges_publish_and_retire(self):
        import types

        registry = GaugeRegistry()
        entries = [
            {"slot": "entry0", "bytes": 128, "rows": 8,
             "shape": (8, 2), "mode": "single", "tenant": "t1",
             "age_s": 1.0},
            {"slot": "entry1", "bytes": 256, "rows": 8,
             "shape": (8, 2), "mode": "single", "tenant": None,
             "age_s": 0.5},
        ]
        resident = types.SimpleNamespace(
            entries=lambda now=None: list(entries)
        )
        service = types.SimpleNamespace(_resident=resident)
        plane = SolverIntrospection(
            enabled=True, registry=registry,
            recorder=FlightRecorder(), stats_source=lambda: [],
        )
        plane.service = service
        plane.on_tick()
        vec = registry.gauge("solver", "resident_entry_bytes")
        assert vec.get("entry0", "t1") == 128.0
        assert vec.get("entry1", "-") == 256.0
        # LRU churn: entry1 evicted — its series must RETIRE
        del entries[1]
        plane.on_tick()
        assert vec.get("entry1", "-") is None
        assert vec.get("entry0", "t1") == 128.0


class TestResidentEntries:
    def test_entries_carry_bytes_rows_tenant_age(self):
        from karpenter_tpu.solver.resident import ResidentFleetState

        resident = ResidentFleetState(scatter="never")
        inputs = _binpack_inputs()
        stacked, kind = resident.obtain(
            inputs, (8, 4, 2, 1, 1), ("single",),
            lambda tree: tree, tenant="t7", now=100.0,
        )
        assert kind == "rebuild"
        entries = resident.entries(now=103.5)
        assert len(entries) == 1
        entry = entries[0]
        assert entry["tenant"] == "t7"
        assert entry["age_s"] == 3.5
        assert entry["rows"] == 8
        assert entry["bytes"] == resident.resident_bytes()
        assert entry["bytes"] > 0


# -- the runtime worlds -------------------------------------------------------


def _churn_world(tmp_path=None, introspect=True, storm_threshold=4,
                 **options_kw):
    """A compact watch-fed churn world (the bench _churn_runtime
    shape): every tick toggles a churn pod so the encode memo misses
    and the tick pays a real solve through the service."""
    from karpenter_tpu.api.core import (
        Node,
        NodeCondition,
        NodeSpec,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        resource_list,
    )
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
        ScalingRules,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer,
        MetricsProducerSpec,
        PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    clock = {"now": 1_000_000.0}
    provider = FakeFactory()
    provider.node_replicas["g"] = 3
    runtime = KarpenterRuntime(
        Options(
            introspect=introspect,
            introspect_storm_threshold=storm_threshold,
            journal_dir=str(tmp_path) if tmp_path else None,
            **options_kw,
        ),
        cloud_provider_factory=provider,
        clock=lambda: clock["now"],
    )
    # force the compiled XLA path: "auto" resolves to the numpy host
    # program on the CPU test backend, which exercises no compile
    # cache at all — the ledger/storm pins need the jitted path (the
    # numpy/XLA bit-parity contract keeps decisions identical)
    runtime.solver_service.backend = "xla"
    store = runtime.store
    store.create(Node(
        metadata=ObjectMeta(name="n1", labels={"pool": "a"}),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu="8", memory="16Gi", pods="16"),
            conditions=[NodeCondition("Ready", "True")],
        ),
    ))
    store.create(Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec()))
    store.create(MetricsProducer(
        metadata=ObjectMeta(name="pending"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "a"}, node_group_ref="g",
            )
        ),
    ))
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="g"),
        spec=ScalableNodeGroupSpec(
            replicas=3, type="FakeNodeGroup", id="g"
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="g"
            ),
            min_replicas=1, max_replicas=100,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
            behavior=Behavior(
                scale_down=ScalingRules(stabilization_window_seconds=0)
            ),
        ),
    ))
    gauge = runtime.registry.register("queue", "length")
    gauge.set("q", "default", 12.0)
    flip = {"high": False}

    def tick():
        from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec

        try:
            runtime.store.delete("Pod", "default", "churn-pod")
        except KeyError:
            runtime.store.create(Pod(
                metadata=ObjectMeta(name="churn-pod"), spec=PodSpec()
            ))
        flip["high"] = not flip["high"]
        gauge.set("q", "default", 20.0 if flip["high"] else 12.0)
        clock["now"] += 61.0
        runtime.manager._due = {k: 0.0 for k in runtime.manager._due}
        runtime.manager.reconcile_all()

    return runtime, provider, tick


class TestSteadyStateCompileGuard:
    def test_zero_new_compiles_past_warmup(self, fresh_recorder):
        """The steady-state compile-count regression guard: the churn
        world, N ticks past warm-up, records ZERO new compile-ledger
        entries — the jit-cache-key discipline pin."""
        runtime, _provider, tick = _churn_world()
        try:
            for _ in range(5):  # warm-up: compiles + first encodes
                tick()
            plane = runtime.solver_introspection
            before = plane.ledger.records_total
            misses_before = (
                runtime.solver_service.stats.compile_cache_misses
            )
            for _ in range(8):
                tick()
            assert plane.ledger.records_total == before, (
                "steady-state churn ticks must not compile: "
                f"{plane.ledger.tail()}"
            )
            assert (
                runtime.solver_service.stats.compile_cache_misses
                == misses_before
            )
        finally:
            runtime.close()

    def test_zero_new_compiles_past_warmup_fused(self, fresh_recorder):
        """The fused-family extension of the guard: the same churn
        world with --fused-tick routes every steady-state tick through
        the ONE fused program, and N ticks past warm-up still record
        ZERO new compile-ledger rows — the fused compile key (shape
        buckets + stage presence) holds steady under churn."""
        runtime, _provider, tick = _churn_world(fused_tick=True)
        try:
            for _ in range(5):  # warm-up: compiles + first encodes
                tick()
            service = runtime.solver_service
            assert service.stats.fused_dispatches > 0, (
                "--fused-tick must actually route the tick through "
                "the fused program"
            )
            plane = runtime.solver_introspection
            before = plane.ledger.records_total
            misses_before = service.stats.compile_cache_misses
            dispatched = service.stats.fused_dispatches
            for _ in range(8):
                tick()
            assert service.stats.fused_dispatches > dispatched
            assert plane.ledger.records_total == before, (
                "steady-state fused ticks must not compile: "
                f"{plane.ledger.tail()}"
            )
            assert (
                service.stats.compile_cache_misses == misses_before
            )
        finally:
            runtime.close()


class TestCompileStormChaos:
    def test_reset_caches_storm_trips_one_dump(
        self, tmp_path, fresh_recorder
    ):
        """ISSUE 15 chaos acceptance: a forced compile storm
        (reset_caches mid-run) trips exactly ONE compile_storm
        flight-recorder dump, the self-SLO device-memory source stays
        quiet, and the steady-state guard is green after re-warm-up."""
        runtime, _provider, tick = _churn_world(
            tmp_path=tmp_path, storm_threshold=1,
        )
        try:
            plane = runtime.solver_introspection
            for _ in range(5):  # warm-up; the taper must not trip
                tick()
            assert plane.storms_total == 0
            # the forced storm: a mid-run cache reset (the recovery-
            # boot seam) makes the next tick recompile its rungs
            runtime.solver_service.reset_caches()
            for _ in range(3):
                tick()
            assert plane.storms_total == 1
            dumps = [
                p.name for p in tmp_path.iterdir()
                if p.name.startswith("flightrecorder-")
                and "compile_storm" in p.name
            ]
            assert len(dumps) == 1, dumps
            # the self-SLO device-memory source stayed quiet (CPU
            # backend: no memory stats -> no events either way)
            assert plane.memory_source() is None
            assert runtime.selfslo.scoreboard().get(
                "device_memory"
            ) == "off"
            # re-warmed: the steady-state guard is green again
            before = plane.ledger.records_total
            for _ in range(4):
                tick()
            assert plane.ledger.records_total == before
            assert plane.storms_total == 1  # still the one incident
        finally:
            runtime.close()


class TestIntrospectOffPin:
    def test_off_is_byte_identical_and_mark_free(self, fresh_recorder):
        """--introspect off (the default): the desired-replica trail is
        byte-identical with the plane on or off, and the off path
        records nothing — mirroring the tracing/provenance off pins."""

        def run(introspect, ticks=8):
            runtime, provider, tick = _churn_world(
                introspect=introspect
            )
            trail = []
            try:
                for _ in range(ticks):
                    tick()
                    trail.append(provider.node_replicas["g"])
                records = (
                    runtime.solver_introspection.ledger.records_total
                )
            finally:
                runtime.close()
            return trail, records

        on_trail, on_records = run(True)
        assert on_records > 0, "enabled world must record compiles"
        off_trail, off_records = run(False)
        assert off_trail == on_trail, (
            "introspection observes; it must never change a decision"
        )
        assert off_records == 0
        assert off_trail  # the world actually actuated


class TestDebugSolverEndpoint:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read())

    def test_posture_document(self, fresh_recorder):
        registry = GaugeRegistry()
        service = SolverService(registry=registry, backend="xla")
        plane = SolverIntrospection(
            enabled=True, registry=registry,
            recorder=FlightRecorder(),
            stats_source=lambda: [{
                "device": "tpu:0", "bytes_in_use": 10,
                "bytes_limit": 100,
            }],
        ).attach(service)
        server = MetricsServer(
            registry, port=0, host="127.0.0.1", introspection=plane
        )
        port = server.start()
        try:
            service.solve(_binpack_inputs())
            plane.on_tick()
            status, doc = self._get(
                f"http://127.0.0.1:{port}/debug/solver"
            )
            assert status == 200
            assert doc["enabled"] is True
            assert doc["compile"]["records_total"] == 1
            assert doc["compile"]["by_family"] == {"solve": 1}
            assert doc["compile"]["cache"]["misses"] == 1
            assert doc["compile"]["cache"]["rungs"]["solve"]
            assert doc["compile"]["ledger_tail"][0]["family"] == "solve"
            assert doc["backend"]["state"] == "healthy"
            assert doc["queue"]["requests"] == 1
            assert doc["queue"]["depth"] == 0
            assert doc["shard"]["broken"] is False
            assert doc["device_memory"]["devices"][0]["device"] == (
                "tpu:0"
            )
            assert "resident" in doc
            # the ledger tail honors ?limit=
            _, limited = self._get(
                f"http://127.0.0.1:{port}/debug/solver?limit=0"
            )
            assert limited["compile"]["ledger_tail"] == []
        finally:
            server.stop()
            service.close()

    def test_unwired_endpoint_reports_disabled(self):
        server = MetricsServer(GaugeRegistry(), port=0, host="127.0.0.1")
        port = server.start()
        try:
            status, doc = self._get(
                f"http://127.0.0.1:{port}/debug/solver"
            )
            assert status == 200
            assert doc == {"enabled": False}
        finally:
            server.stop()

    def test_disabled_plane_exposes_no_posture(self):
        """--introspect off is the opt-in for the WHOLE surface: a
        wired-but-disabled plane must not leak compile rungs, resident
        tenants, or queue internals through /debug/solver."""
        registry = GaugeRegistry()
        service = SolverService(registry=registry, backend="xla")
        plane = SolverIntrospection(
            enabled=False, registry=registry,
            recorder=FlightRecorder(),
        ).attach(service)
        server = MetricsServer(
            registry, port=0, host="127.0.0.1", introspection=plane
        )
        port = server.start()
        try:
            status, doc = self._get(
                f"http://127.0.0.1:{port}/debug/solver"
            )
            assert status == 200
            assert doc == {"enabled": False}
        finally:
            server.stop()
            service.close()


class TestIntrospectOverheadGuard:
    def test_enabled_vs_disabled_tick_overhead(self, fresh_recorder):
        """The wall-clock guard with generous flake headroom: `make
        bench-introspect` publishes the honest <=2% number
        (docs/BENCHMARKS.md); this pin catches gross regressions."""
        import time

        runtime, _provider, tick = _churn_world()
        plane = runtime.solver_introspection

        def run(enabled, ticks=10):
            plane.enabled = enabled
            times = []
            for _ in range(ticks):
                t0 = time.perf_counter()
                tick()
                times.append(time.perf_counter() - t0)
            return float(np.percentile(times, 50))

        try:
            for _ in range(4):  # warm-up
                tick()
            off = run(False)
            on = run(True)
        finally:
            runtime.close()
        assert on <= off * 1.75 + 0.002, (
            f"introspection overhead p50 {off * 1e3:.3f}ms -> "
            f"{on * 1e3:.3f}ms"
        )
