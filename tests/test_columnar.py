"""Columnar pending-pod cache: incremental maintenance + solver-input
equivalence with the store.list oracle path under churn.

The cache (store/columnar.py) must produce EXACTLY the outputs of the
original list+encode path for any store history — adds, request changes,
binding (pod gets a nodeName), deletion, slot reuse, universe growth —
because the solver is permutation-invariant over pods.
"""

import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Toleration,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.metrics.producers.pendingcapacity import solve_pending
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.store import Store
from karpenter_tpu.store.columnar import PendingPodCache
from karpenter_tpu.utils.quantity import Quantity


def pod(name, cpu="100m", mem="128Mi", node=None, selector=None,
        tolerations=None, extra=None, phase="Pending"):
    from karpenter_tpu.api.core import PodStatus

    requests = {"cpu": Quantity.parse(cpu), "memory": Quantity.parse(mem)}
    for r, v in (extra or {}).items():
        requests[r] = Quantity.parse(v)
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            node_name=node,
            containers=[Container(requests=requests)],
            node_selector=dict(selector or {}),
            tolerations=list(tolerations or []),
        ),
        status=PodStatus(phase=phase),
    )


def node(name, labels, cpu="32", mem="128Gi", taints=None):
    from karpenter_tpu.api.core import Taint

    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=__import__(
            "karpenter_tpu.api.core", fromlist=["NodeSpec"]
        ).NodeSpec(taints=[Taint(**t) for t in (taints or [])]),
        status=NodeStatus(
            allocatable={
                "cpu": Quantity.parse(cpu),
                "memory": Quantity.parse(mem),
            },
            conditions=[NodeCondition(type="Ready", status="True")],
        ),
    )


def producer(name, selector):
    return MetricsProducer(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(node_selector=dict(selector))
        ),
    )


def statuses(store):
    out = {}
    for mp in store.list("MetricsProducer"):
        s = mp.status.pending_capacity
        out[mp.metadata.name] = None if s is None else (
            s.pending_pods,
            s.additional_nodes_needed,
            s.lp_lower_bound,
            s.unschedulable_pods,
        )
    return out


def solve_both(store, cache, feed=None):
    """Run the oracle (list) path, the pod-cache path, and (when given)
    the full-feed path; return all status maps. Producers are re-fetched
    fresh so statuses don't leak across."""
    results = []
    variants = [{"pod_cache": None}, {"pod_cache": cache}]
    if feed is not None:
        variants.append({"feed": feed})
    for kwargs in variants:
        mps = [
            mp for mp in store.list("MetricsProducer")
            if mp.spec.pending_capacity is not None
        ]
        solve_pending(store, mps, GaugeRegistry(), **kwargs)
        results.append(
            {
                mp.metadata.name: (
                    mp.status.pending_capacity.pending_pods,
                    mp.status.pending_capacity.additional_nodes_needed,
                    mp.status.pending_capacity.lp_lower_bound,
                    mp.status.pending_capacity.unschedulable_pods,
                )
                for mp in mps
            }
        )
    return results


class TestMaintenance:
    def test_add_bind_delete(self):
        store = Store()
        cache = PendingPodCache(store)
        created = store.create(pod("a"))
        store.create(pod("b"))
        assert len(cache) == 2
        created.spec.node_name = "n1"  # scheduled -> no longer pending
        store.update(created)
        assert len(cache) == 1
        store.delete("Pod", "default", "b")
        assert len(cache) == 0

    def test_adopts_preexisting_pods(self):
        store = Store()
        store.create(pod("a"))
        store.create(pod("b", node="n1"))  # bound: not pending
        cache = PendingPodCache(store)
        assert len(cache) == 1

    def test_non_pending_phase_excluded(self):
        store = Store()
        cache = PendingPodCache(store)
        store.create(pod("done", phase="Succeeded"))
        assert len(cache) == 0

    def test_slot_reuse_and_growth(self):
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        for i in range(40):  # forces arena growth
            store.create(pod(f"p{i}"))
        for i in range(0, 40, 2):
            store.delete("Pod", "default", f"p{i}")
        for i in range(40, 60):  # reuses freed slots
            store.create(pod(f"p{i}"))
        assert len(cache) == 40
        snap = cache.snapshot()
        assert int(snap.valid.sum()) == 40

    def test_universe_growth_new_resource_and_label(self):
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        store.create(pod("a"))
        for i in range(20):  # outgrow both column arenas
            store.create(
                pod(
                    f"x{i}",
                    extra={f"vendor.io/res{i}": "1"},
                    selector={f"zone{i}": "z"},
                )
            )
        snap = cache.snapshot()
        assert "vendor.io/res7" in snap.resources
        assert ("zone7", "z") in snap.labels
        row = snap.requests[:, snap.resources.index("vendor.io/res7")]
        assert row.sum() == pytest.approx(1.0)

    def test_snapshot_isolation(self):
        store = Store()
        cache = PendingPodCache(store)
        store.create(pod("a"))
        snap = cache.snapshot()
        before = snap.requests.copy()
        store.create(pod("b", cpu="4"))
        np.testing.assert_array_equal(snap.requests, before)

    def test_modify_requests_reencodes(self):
        store = Store()
        cache = PendingPodCache(store)
        created = store.create(pod("a", cpu="1"))
        created.spec.containers[0].requests["cpu"] = Quantity.parse("2")
        store.update(created)
        snap = cache.snapshot()
        cpu = snap.resources.index("cpu")
        assert snap.requests[:, cpu].max() == pytest.approx(2.0)
        assert len(cache) == 1


class TestCompaction:
    def test_peak_drain_restores_live_cost(self):
        """After an incident peak drains, snapshot size must track the LIVE
        pending set, not the historical high-water mark."""
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        for i in range(600):
            store.create(pod(f"p{i}"))
        assert cache.snapshot().requests.shape[0] >= 600
        for i in range(590):
            store.delete("Pod", "default", f"p{i}")
        snap = cache.snapshot()  # triggers compaction (peak >> live)
        assert snap.requests.shape[0] < 64
        assert int(snap.valid.sum()) == 10

    def test_universe_churn_compacts(self):
        """Per-job selector labels must not accumulate forever."""
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        for i in range(600):  # each adds a unique label, then leaves
            store.create(pod(f"p{i}", selector={f"job{i}": "x"}))
            store.delete("Pod", "default", f"p{i}")
        store.create(pod("steady", selector={"zone": "z"}))
        snap = cache.snapshot()
        assert len(snap.labels) < 16
        assert ("zone", "z") in snap.labels

    def test_compaction_preserves_solver_outputs(self):
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        store.create(node("n0", {"group": "small"}, cpu="8"))
        store.create(producer("small", {"group": "small"}))
        for i in range(400):
            store.create(pod(f"p{i}", cpu="1"))
        for i in range(380):
            store.delete("Pod", "default", f"p{i}")
        oracle, cached = solve_both(store, cache)
        assert oracle == cached
        assert cached["small"][0] == 20


class TestReservationsCache:
    def _reserved(self, store, cache=None, mirror=None):
        from karpenter_tpu.api.metricsproducer import ReservedCapacitySpec
        from karpenter_tpu.metrics.producers.reservedcapacity import (
            ReservedCapacityProducer,
        )

        mp = MetricsProducer(
            metadata=ObjectMeta(name="rc", namespace="default"),
            spec=MetricsProducerSpec(
                reserved_capacity=ReservedCapacitySpec(
                    node_selector={"group": "small"}
                )
            ),
        )
        ReservedCapacityProducer(
            mp, store, registry=GaugeRegistry(),
            reservations=cache, node_mirror=mirror,
        ).reconcile()
        return dict(mp.status.reserved_capacity)

    def test_matches_oracle_under_churn(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import (
            NodeMirror,
            ReservationsCache,
        )

        rng = np.random.default_rng(3)
        store = Store()
        cache = ReservationsCache(store)
        mirror = NodeMirror(store, group_profile)
        store.create(node("n0", {"group": "small"}, cpu="16", mem="64Gi"))
        store.create(node("n1", {"group": "small"}, cpu="8", mem="32Gi"))
        live = {}
        serial = 0
        for _ in range(200):
            action = rng.choice(["add", "rebind", "delete", "resize"])
            if action == "add" or not live:
                name = f"p{serial}"
                serial += 1
                target = rng.choice(["n0", "n1", None])
                store.create(
                    pod(name, cpu=f"{rng.integers(1, 5) * 250}m",
                        mem=f"{rng.integers(1, 9) * 256}Mi", node=target)
                )
                live[name] = True
            elif action == "rebind":
                name = rng.choice(list(live))
                obj = store.get("Pod", "default", name)
                obj.spec.node_name = rng.choice(["n0", "n1"])
                store.update(obj)
            elif action == "delete":
                name = rng.choice(list(live))
                store.delete("Pod", "default", name)
                del live[name]
            else:
                name = rng.choice(list(live))
                obj = store.get("Pod", "default", name)
                obj.spec.containers[0].requests["cpu"] = Quantity.parse(
                    f"{rng.integers(1, 9) * 125}m"
                )
                store.update(obj)
        oracle = self._reserved(store)
        cached = self._reserved(store, cache=cache, mirror=mirror)
        assert oracle == cached  # exact strings, incl. formats

    def test_mixed_format_sums_render_identically(self):
        """Quantity.add adopts the first non-zero operand's format, and the
        cache path accumulates in pod-creation order while the oracle path
        accumulates node-by-node — so mixed-format memory requests used to
        render value-equal but differently-formatted status strings.
        512Mi (binary) + 536870912 (decimal) = 1Gi exactly: binary-first
        renders "1Gi", decimal-first "1073741824". The producer now
        canonicalizes to the capacity side's format (order-stable), so both
        paths must render the SAME string."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import (
            NodeMirror,
            ReservationsCache,
        )

        store = Store()
        cache = ReservationsCache(store)
        mirror = NodeMirror(store, group_profile)
        store.create(node("n0", {"group": "small"}, cpu="16", mem="96Gi"))
        # same node, creation order ("z" first, decimal) opposite to the
        # oracle's sorted-key order ("a" first, binary): the cache's
        # per-node sum adopts decimal, the oracle's adopts binary
        store.create(pod("z", cpu="1", mem="536870912", node="n0"))
        store.create(pod("a", cpu="1", mem="512Mi", node="n0"))
        oracle = self._reserved(store)
        cached = self._reserved(store, cache=cache, mirror=mirror)
        assert oracle == cached
        # capacity is 96Gi (binary), so the canonical rendering is binary
        assert oracle["memory"].endswith(", 1Gi/96Gi")

    def test_unready_nodes_excluded(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import (
            NodeMirror,
            ReservationsCache,
        )
        from karpenter_tpu.api.core import NodeCondition

        store = Store()
        cache = ReservationsCache(store)
        mirror = NodeMirror(store, group_profile)
        store.create(node("ready", {"group": "small"}, cpu="8"))
        broken = node("broken", {"group": "small"}, cpu="8")
        broken.status.conditions = [
            NodeCondition(type="Ready", status="False")
        ]
        store.create(broken)
        store.create(pod("a", cpu="1", node="ready"))
        store.create(pod("b", cpu="1", node="broken"))  # must not count
        oracle = self._reserved(store)
        cached = self._reserved(store, cache=cache, mirror=mirror)
        assert oracle == cached
        assert oracle["cpu"].startswith("12.50%")  # 1 of 8, broken excluded


class TestLazyFactoryCache:
    def test_not_created_without_pending_producer(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.metrics.producers import ProducerFactory

        store = Store()
        factory = ProducerFactory(store, FakeFactory(), registry=GaugeRegistry())
        assert factory._pending_feed is None
        assert factory.pending_feed() is factory.pending_feed()  # memoized


class TestEquivalence:
    def _cluster(self, store):
        store.create(node("n0", {"group": "small"}, cpu="8", mem="32Gi"))
        store.create(
            node(
                "n1",
                {"group": "big"},
                cpu="64",
                mem="256Gi",
                taints=[
                    {"key": "accel", "value": "tpu", "effect": "NoSchedule"}
                ],
            )
        )
        store.create(producer("small", {"group": "small"}))
        store.create(producer("big", {"group": "big"}))

    def test_simple_equivalence(self):
        store = Store()
        cache = PendingPodCache(store)
        self._cluster(store)
        for i in range(10):
            store.create(pod(f"p{i}", cpu="2"))
        oracle, cached = solve_both(store, cache)
        assert oracle == cached
        assert oracle["small"][0] > 0

    def test_equivalence_with_tolerations_and_selectors(self):
        store = Store()
        cache = PendingPodCache(store)
        self._cluster(store)
        tol = [
            Toleration(
                key="accel", operator="Equal", value="tpu",
                effect="NoSchedule",
            )
        ]
        for i in range(6):
            store.create(
                pod(f"t{i}", cpu="16", tolerations=tol,
                    selector={"group": "big"})
            )
        for i in range(6):
            store.create(pod(f"u{i}", cpu="16"))  # intolerant of big's taint
        oracle, cached = solve_both(store, cache)
        assert oracle == cached
        assert cached["big"][0] == 6  # tolerant+selected pods land on big

    def test_feed_equivalence_with_node_and_producer_churn(self):
        """The full feed (pod arena + node-profile memo + producer index)
        must match the oracle after nodes and producers change too."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, group_profile)
        cache = PendingPodCache(store)
        self._cluster(store)
        for i in range(12):
            store.create(pod(f"p{i}", cpu="2"))
        # node churn: grow the small group with a bigger node, cordon none
        store.create(node("n2", {"group": "small"}, cpu="16", mem="64Gi"))
        # producer churn: add a group after the feed exists, remove later
        store.create(producer("late", {"group": "big"}))
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        store.delete("MetricsProducer", "default", "late")
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        assert "late" not in fed

    def test_equivalence_under_random_churn(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        rng = np.random.default_rng(7)
        store = Store()
        cache = PendingPodCache(store, capacity=16)
        feed = PendingFeed(store, group_profile)
        self._cluster(store)
        live = {}
        serial = 0
        for _ in range(300):
            action = rng.choice(["add", "bind", "delete", "resize"])
            if action == "add" or not live:
                name = f"p{serial}"
                serial += 1
                extra = (
                    {"vendor.io/widget": "2"} if rng.random() < 0.2 else None
                )
                selector = {"group": "big"} if rng.random() < 0.3 else None
                obj = store.create(
                    pod(
                        name,
                        cpu=f"{rng.integers(1, 9)}",
                        selector=selector,
                        extra=extra,
                    )
                )
                live[name] = obj
            elif action == "bind":
                name = rng.choice(list(live))
                obj = store.get("Pod", "default", name)
                obj.spec.node_name = "n0"
                store.update(obj)
                del live[name]
            elif action == "delete":
                name = rng.choice(list(live))
                store.delete("Pod", "default", name)
                del live[name]
            else:  # resize
                name = rng.choice(list(live))
                obj = store.get("Pod", "default", name)
                obj.spec.containers[0].requests["cpu"] = Quantity.parse(
                    f"{rng.integers(1, 17)}"
                )
                store.update(obj)
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed


class TestSolveCaching:
    """The tick-collapse caches: snapshot memo (same object per unchanged
    generation), encode memo (same BinPackInputs object per unchanged
    fleet), and their invalidation on pod/node/producer churn. These are
    what turn an unchanged 100k-pod tick into a single device round-trip
    (see _dispatch_and_record's packed fetch + ops/binpack._device_resident)."""

    def test_snapshot_identity_stable_until_mutation(self):
        store = Store()
        cache = PendingPodCache(store)
        store.create(pod("p0"))
        s1 = cache.snapshot()
        assert cache.snapshot() is s1
        store.create(pod("p1"))
        s2 = cache.snapshot()
        assert s2 is not s1
        assert s2.generation > s1.generation
        store.delete("Pod", "default", "p1")
        s3 = cache.snapshot()
        assert s3 is not s2
        # non-mutating churn (delete of an unknown pod) keeps the memo
        assert cache.snapshot() is s3

    def test_encode_memo_reuse_and_invalidation(self, monkeypatch):
        import karpenter_tpu.metrics.producers.pendingcapacity as PC
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, PC.group_profile)
        store.create(node("n0", {"group": "g"}, cpu="8", mem="32Gi"))
        store.create(producer("mp", {"group": "g"}))
        for i in range(3):
            store.create(pod(f"p{i}"))

        calls = []
        real = PC.encode_snapshot

        def counting(*args, **kwargs):
            calls.append(1)
            return real(*args, **kwargs)

        monkeypatch.setattr(PC, "encode_snapshot", counting)
        solves = []
        from karpenter_tpu.ops import binpack as B

        def counting_solver(inputs, **kwargs):
            solves.append(1)
            return B.solve(inputs, **kwargs)

        registry = GaugeRegistry()

        def tick():
            mps = store.list("MetricsProducer")
            PC.solve_pending(
                store, mps, registry, feed=feed, solver=counting_solver
            )
            return registry.gauge(
                PC.SUBSYSTEM, PC.ADDITIONAL_NODES_NEEDED
            ).get("mp", "default")

        first = tick()
        assert len(calls) == 1
        assert tick() == first  # memo hit: same outputs, no re-encode
        assert len(calls) == 1
        # an unchanged tick skips the DEVICE too: the memoized host
        # outputs are republished without a solve
        assert len(solves) == 1
        store.create(pod("p9"))  # pod churn invalidates
        tick()
        assert len(calls) == 2
        assert len(solves) == 2  # fresh inputs MUST re-solve (no stale outputs)
        tick()
        assert len(calls) == 2
        assert len(solves) == 2  # and the new outputs are memoized again
        store.create(node("n1", {"group": "g"}, cpu="4", mem="16Gi"))
        tick()  # node churn invalidates (profile shape changed)
        assert len(calls) == 3
        store.create(producer("mp2", {"group": "g"}))
        tick()  # producer-set churn invalidates (group axis changed)
        assert len(calls) == 4


class TestShapeDedup:
    """_dedup_rows + pod_weight: the encoder collapses identical pods into
    weighted shape rows (what turns the 100k-pod upload into KBs)."""

    def test_duplicate_pods_collapse_with_counts(self):
        import karpenter_tpu.metrics.producers.pendingcapacity as PC

        store = Store()
        cache = PendingPodCache(store)
        for i in range(50):
            store.create(pod(f"a{i}", cpu="2"))      # 50 x shape A
        for i in range(30):
            store.create(pod(f"b{i}", cpu="500m"))   # 30 x shape B
        store.create(pod("c0", cpu="2", selector={"zone": "z"}))  # 1 x C
        snap = cache.snapshot()
        profiles = [({"cpu": 8.0, "memory": 64.0, "pods": 110.0},
                     set(), set())]
        inputs = PC.encode_snapshot(snap, profiles)
        weights = np.asarray(inputs.pod_weight)
        live = sorted(int(w) for w in weights[weights > 0])
        assert live == [1, 30, 50]  # 81 pods -> 3 weighted shape rows
        # aggregates over the weighted solve equal the pod count
        from karpenter_tpu.ops import binpack as B

        out = B.binpack(inputs, buckets=16)
        assert int(np.sum(np.asarray(out.assigned_count))) + int(
            out.unschedulable
        ) == 81

    def test_incremental_dedup_equals_full_unique_under_churn(self):
        """The watch-maintained dedup (PendingPodCache._dedup_slots) must
        agree with the np.unique-over-all-rows fallback for any history:
        adds, mutations that change a pod's shape, deletes, slot reuse,
        and compaction. Weights are compared as multisets keyed by row
        content (row ORDER is canonicalized by byte-sort either way)."""
        import dataclasses

        from karpenter_tpu.metrics.producers.pendingcapacity import encoder as PCE

        rng = np.random.default_rng(11)
        store = Store()
        cache = PendingPodCache(store)
        cpus = ["100m", "250m", "2", "4"]
        live = {}
        for step in range(600):
            op = rng.random()
            if op < 0.55 or not live:
                name = f"p{step}"
                cpu = str(rng.choice(cpus))
                sel = {"zone": "z"} if rng.random() < 0.3 else None
                store.create(pod(name, cpu=cpu, selector=sel))
                live[name] = True
            elif op < 0.8:
                victim = str(rng.choice(list(live)))
                store.delete("Pod", "default", victim)
                del live[victim]
            else:
                victim = str(rng.choice(list(live)))
                store.update(pod(victim, cpu=str(rng.choice(cpus))))
        snap = cache.snapshot()
        assert snap.dedup_idx is not None
        inc_idx, inc_w = PCE._dedup_rows(snap)
        # force the np.unique fallback on the same snapshot content
        full = dataclasses.replace(snap, dedup_idx=None, dedup_weight=None)
        uni_idx, uni_w = PCE._dedup_rows(full)

        def keyed(idx, weights, include_invalid):
            out = {}
            for i, w in zip(idx, weights):
                if not snap.valid[i] and not include_invalid:
                    continue
                key = (
                    snap.requests[i].tobytes(),
                    snap.required[i].tobytes(),
                    int(snap.shape_id[i]),
                    bool(snap.valid[i]),
                )
                out[key] = out.get(key, 0) + int(w)
            return out

        # the fallback also emits the collapsed free-slot (invalid) row;
        # the incremental path drops it — output-equal, filtered here
        assert keyed(inc_idx, inc_w, True) == keyed(uni_idx, uni_w, False)
        assert sum(keyed(inc_idx, inc_w, True).values()) == len(live)

    def test_node_affinity_constrains_the_solve(self):
        """Required node affinity (NotIn) steers pods off a group on every
        encode path, and pods differing ONLY by affinity dedup apart."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, group_profile)
        cache = PendingPodCache(store)
        store.create(
            node("n0", {"group": "a", "disk": "hdd"}, cpu="8", mem="32Gi")
        )
        store.create(
            node("n1", {"group": "b", "disk": "ssd"}, cpu="8", mem="32Gi")
        )
        store.create(producer("mpa", {"group": "a"}))
        store.create(producer("mpb", {"group": "b"}))
        not_hdd = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=(
                    NodeSelector(
                        node_selector_terms=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    NodeSelectorRequirement(
                                        key="disk",
                                        operator="NotIn",
                                        values=["hdd"],
                                    )
                                ]
                            )
                        ]
                    )
                )
            )
        )
        # 4 unconstrained pods (first-feasible: group a) + 4 identical
        # pods that refuse hdd (must go to group b)
        for i in range(4):
            store.create(pod(f"free{i}", cpu="2"))
        for i in range(4):
            p = pod(f"ssd{i}", cpu="2")
            p.spec.affinity = not_hdd
            store.create(p)
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        assert oracle["mpa"][0] == 4 and oracle["mpb"][0] == 4
        assert oracle["mpa"][3] == 0 and oracle["mpb"][3] == 0  # none unsched
        snap = cache.snapshot()
        assert len(snap.dedup_idx) == 2  # same size/labels, split by affinity

        # once every affinity pod is gone, the encode drops back to the
        # maskless (no pod_group_forbidden) path even though the shape
        # registry still remembers the affinity shape
        import karpenter_tpu.metrics.producers.pendingcapacity as PC

        for i in range(4):
            store.delete("Pod", "default", f"ssd{i}")
        snap = cache.snapshot()
        assert any(s for s in snap.affinity_shapes)  # registry not pruned
        profiles = [
            ({"cpu": 8.0, "memory": 32.0 * 1024**3, "pods": 110.0},
             {("group", "a"), ("disk", "hdd")}, set()),
        ]
        inputs = PC.encode_snapshot(snap, profiles)
        assert inputs.pod_group_forbidden is None

    def test_preferred_affinity_steers_assignment(self):
        """A pod preferring ssd (weight 80) goes to the ssd group even
        though the hdd group comes first in producer order; identical on
        every encode path; preferences never rescue infeasibility."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, group_profile)
        cache = PendingPodCache(store)
        store.create(
            node("n0", {"group": "a", "disk": "hdd"}, cpu="8", mem="32Gi")
        )
        store.create(
            node("n1", {"group": "b", "disk": "ssd"}, cpu="8", mem="32Gi")
        )
        store.create(producer("mpa", {"group": "a"}))
        store.create(producer("mpb", {"group": "b"}))
        prefer_ssd = Affinity(
            node_affinity=NodeAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    PreferredSchedulingTerm(
                        weight=80,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="disk", operator="In", values=["ssd"]
                                )
                            ]
                        ),
                    )
                ]
            )
        )
        for i in range(3):
            store.create(pod(f"free{i}", cpu="2"))  # first-feasible -> a
        for i in range(3):
            p = pod(f"pref{i}", cpu="2")
            p.spec.affinity = prefer_ssd
            store.create(p)
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        assert oracle["mpa"][0] == 3  # unpreferring pods: first feasible
        assert oracle["mpb"][0] == 3  # preferring pods steered to ssd
        assert oracle["mpa"][3] == 0 and oracle["mpb"][3] == 0
        # a preference for a group that can't fit the pod does NOT make it
        # feasible: a 32-cpu pod preferring ssd is simply unschedulable
        big = pod("big", cpu="32")
        big.spec.affinity = prefer_ssd
        store.create(big)
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        assert oracle["mpa"][3] == 1 and oracle["mpb"][3] == 1  # global count

    def test_affinity_shape_registry_compacts_after_churn(self):
        """A stream of Jobs each pinning a DISTINCT affinity must not grow
        the shape registry unboundedly: _needs_compaction watches
        _affinity_shapes like the toleration-shape universe."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        def pin(zone):
            return Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        NodeSelector(
                            node_selector_terms=[
                                NodeSelectorTerm(
                                    match_expressions=[
                                        NodeSelectorRequirement(
                                            key="zone",
                                            operator="In",
                                            values=[zone],
                                        )
                                    ]
                                )
                            ]
                        )
                    )
                )
            )

        store = Store()
        cache = PendingPodCache(store)
        for i in range(300):  # distinct shapes, all churned away
            p = pod(f"job{i}", cpu="1")
            p.spec.affinity = pin(f"z{i}")
            store.create(p)
            store.delete("Pod", "default", f"job{i}")
        for i in range(5):  # small live set
            p = pod(f"live{i}", cpu="1")
            p.spec.affinity = pin("keep")
            store.create(p)
        snap = cache.snapshot()  # snapshot() compacts when peak >> live
        assert len(snap.affinity_shapes) < 300 // 4
        assert len(cache) == 5

    def test_effective_requests_drive_the_solve(self):
        """A pod whose init phase dwarfs its main phase must be packed by
        the init size (k8s scheduler fit semantics), on BOTH the feed and
        the oracle path."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, group_profile)
        cache = PendingPodCache(store)
        store.create(node("n0", {"group": "g"}, cpu="8", mem="32Gi"))
        store.create(producer("mp", {"group": "g"}))
        # main phase 100m, init phase 4 cpu: 8-cpu nodes hold 2 each (by
        # init size), NOT 80 (by main size)
        for i in range(10):
            p = pod(f"p{i}", cpu="100m")
            p.spec.init_containers = [
                Container(requests={"cpu": Quantity.parse("4")})
            ]
            store.create(p)
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        pending, nodes_needed, lp, unsched = oracle["mp"]
        assert pending == 10 and unsched == 0
        assert nodes_needed == 5  # 10 pods x 4 cpu / 8 cpu per node

    def test_dedup_survives_pending_set_draining_to_zero(self):
        """All pods scheduling away (the success state) leaves hi > 0
        freed arena rows with an EMPTY incremental dedup — the encode
        must yield the empty solve, not crash on a 0-row gather."""
        import karpenter_tpu.metrics.producers.pendingcapacity as PC
        from karpenter_tpu.metrics.producers.pendingcapacity import encoder as PCE

        store = Store()
        cache = PendingPodCache(store)
        for i in range(5):
            store.create(pod(f"p{i}", cpu="1"))
        for i in range(5):
            store.delete("Pod", "default", f"p{i}")
        snap = cache.snapshot()
        assert snap.requests.shape[0] > 0 and len(snap.dedup_idx) == 0
        idx, weights = PCE._dedup_rows(snap)
        assert len(idx) == 0 and len(weights) == 0
        profiles = [({"cpu": 8.0, "memory": 64.0, "pods": 110.0},
                     set(), set())]
        inputs = PC.encode_snapshot(snap, profiles)
        from karpenter_tpu.ops import binpack as B

        out = B.binpack(inputs, buckets=16)
        assert int(np.sum(np.asarray(out.assigned_count))) == 0
        assert int(out.unschedulable) == 0

    def test_dedup_statuses_equal_across_paths(self):
        """The dedup must be output-invisible: feed path, pod-cache path,
        and oracle path still agree after heavy duplication + churn."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
        )
        from karpenter_tpu.store.columnar import PendingFeed

        store = Store()
        feed = PendingFeed(store, group_profile)
        cache = PendingPodCache(store)
        store.create(node("n0", {"group": "small"}, cpu="8", mem="32Gi"))
        store.create(node("n1", {"group": "big"}, cpu="64", mem="256Gi"))
        store.create(producer("small", {"group": "small"}))
        store.create(producer("big", {"group": "big"}))
        for i in range(40):
            store.create(pod(f"p{i}", cpu="2"))
        for i in range(20):
            store.create(pod(f"q{i}", cpu="16"))  # only fits big
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
        for i in range(10):
            store.delete("Pod", "default", f"p{i}")
        oracle, cached, fed = solve_both(store, cache, feed)
        assert oracle == cached == fed
