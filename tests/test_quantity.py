"""Quantity parse/format fidelity against k8s resource.Quantity behavior the
reference's status strings depend on (reservedcapacity/producer.go:63-86)."""

import pytest

from karpenter_tpu.utils.quantity import Quantity, parse_quantity


class TestParse:
    @pytest.mark.parametrize(
        "text,expected_float",
        [
            ("1100m", 1.1),
            ("2100m", 2.1),
            ("99", 99.0),
            ("1Gi", 1024.0**3),
            ("25Gi", 25 * 1024.0**3),
            ("128500Mi", 128500 * 1024.0**2),
            ("50", 50.0),
            ("16300m", 16.3),
            ("1.5", 1.5),
            ("100k", 100_000.0),
            ("2M", 2_000_000.0),
            ("1e3", 1000.0),
            ("500u", 0.0005),
            ("-2", -2.0),
        ],
    )
    def test_values(self, text, expected_float):
        assert parse_quantity(text).to_float() == pytest.approx(expected_float)

    def test_rejects_garbage(self):
        for bad in ["", "abc", "1..2", "1Qi", "--1"]:
            with pytest.raises(ValueError):
                parse_quantity(bad)


class TestCanonicalFormat:
    """Golden strings from the reference MP suite
    (pkg/controllers/metricsproducer/v1alpha1/suite_test.go:101-105)."""

    def test_cpu_millis_sum(self):
        total = Quantity()
        for q in ["1100m", "2100m", "3300m", "1100m"]:
            total = total.add(parse_quantity(q))
        assert str(total) == "7600m"

    def test_cpu_capacity_sum(self):
        total = Quantity()
        for _ in range(3):
            total = total.add(parse_quantity("16300m"))
        assert str(total) == "48900m"

    def test_memory_binary_sum(self):
        total = Quantity()
        for q in ["1Gi", "25Gi", "50Gi", "1Gi"]:
            total = total.add(parse_quantity(q))
        assert str(total) == "77Gi"

    def test_memory_capacity_stays_mi(self):
        total = Quantity()
        for _ in range(3):
            total = total.add(parse_quantity("128500Mi"))
        assert str(total) == "385500Mi"

    def test_pods_plain(self):
        total = Quantity()
        for _ in range(4):
            total = total.add(parse_quantity("1"))
        assert str(total) == "4"

    def test_zero(self):
        assert str(Quantity()) == "0"

    def test_integer_millis_collapse(self):
        # 2000m == 2: canonical form drops to the base unit
        assert str(parse_quantity("2000m")) == "2"

    def test_binary_promotes(self):
        total = parse_quantity("512Mi").add(parse_quantity("512Mi"))
        assert str(total) == "1Gi"

    def test_zero_adopts_format_of_first_operand(self):
        assert str(Quantity().add(parse_quantity("1Gi"))) == "1Gi"
        assert str(Quantity().add(parse_quantity("1100m"))) == "1100m"

    def test_nonzero_keeps_own_format(self):
        # a decimal accumulator that already has value keeps decimal format
        total = parse_quantity("1").add(parse_quantity("1Gi"))
        assert str(total) == "1073741825"


class TestArithmetic:
    def test_milli_rounding(self):
        assert parse_quantity("1100m").milli() == 1100
        assert parse_quantity("1").milli() == 1000
        assert parse_quantity("1n").milli() == 1  # rounds up

    def test_comparison(self):
        assert parse_quantity("500m") < parse_quantity("1")
        assert parse_quantity("1Gi") <= parse_quantity("1024Mi")
        assert parse_quantity("1Gi") == parse_quantity("1024Mi")
