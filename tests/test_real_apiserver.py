"""Real-apiserver conformance tier (env-gated).

The fake apiserver (tests/fake_apiserver.py) is a protocol double the
rest of the suite self-referees against; this module pins the SAME
KubeClient/KubeStore semantics — chunked LIST + continue tokens, watch
replay/delete events, informer mirror convergence, merge-patch status,
too-old watch recovery — against a GENUINE kube-apiserver, the way the
reference's envtest boots a real one (reference:
pkg/test/environment/local.go:53-157).

Gate: set KARPENTER_TEST_REAL_APISERVER to the apiserver base URL
(e.g. from `kind`: https://127.0.0.1:<port>). Optional auth env:
KARPENTER_TEST_REAL_APISERVER_TOKEN (bearer token),
KARPENTER_TEST_REAL_APISERVER_CA (CA bundle path),
KARPENTER_TEST_REAL_APISERVER_INSECURE=1 (skip TLS verify — dev only).
Documented in docs/OPERATIONS.md and docs/DEVELOPER_GUIDE.md.

Isolation follows the reference's random-namespace pattern
(namespace.go:45-54): each test run creates its own namespace and
deletes it on teardown, so parallel runs and leftover state never
collide.
"""

from __future__ import annotations

import os
import time
import uuid

import pytest

from karpenter_tpu.store.kube import KubeClient, KubeStore

BASE_URL = os.environ.get("KARPENTER_TEST_REAL_APISERVER", "")

pytestmark = pytest.mark.skipif(
    not BASE_URL,
    reason="KARPENTER_TEST_REAL_APISERVER not set (real-apiserver tier)",
)


def _client(timeout: float = 30.0) -> KubeClient:
    return KubeClient(
        base_url=BASE_URL,
        token=os.environ.get("KARPENTER_TEST_REAL_APISERVER_TOKEN"),
        ca_file=os.environ.get("KARPENTER_TEST_REAL_APISERVER_CA"),
        insecure=bool(
            os.environ.get("KARPENTER_TEST_REAL_APISERVER_INSECURE")
        ),
        timeout=timeout,
    )


@pytest.fixture(scope="module")
def namespace():
    """Random-named namespace per run (the reference's isolation
    pattern); removed on teardown so reruns start clean."""
    client = _client()
    name = f"karpenter-conf-{uuid.uuid4().hex[:8]}"
    client._request(
        "POST",
        "api/v1/namespaces",
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": name}},
    )
    yield name
    client._request("DELETE", f"api/v1/namespaces/{name}")


@pytest.fixture()
def client():
    return _client()


def create_pod(client, name, namespace):
    """Create a pod via a RAW real-apiserver-shaped document (the model
    codec serializes only the scheduling-relevant subset, which real
    admission rejects: containers need an image, requests nest under
    resources). Reads/watches flow back through the lenient decode the
    production mirror uses. The impossible nodeSelector keeps the pod
    Pending forever: the kubelet never adopts it, so it cannot race the
    suite's own status writes (TestStatusPatch) and deletes settle
    without waiting on a node."""
    client._request(
        "POST",
        f"api/v1/namespaces/{namespace}/pods",
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "nodeSelector": {"karpenter-conformance/no-such": "node"},
                "containers": [
                    {
                        "name": "main",
                        "image": "registry.k8s.io/pause:3.9",
                        "resources": {
                            "requests": {"cpu": "10m", "memory": "16Mi"}
                        },
                    }
                ],
            },
        },
    )


def wait_until(predicate, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TestChunkedList:
    """API Concepts 'Retrieving large results sets in chunks': the
    continue protocol against the genuine implementation."""

    def test_small_pages_span_the_collection(self, client, namespace):
        for i in range(5):
            create_pod(client, f"page-{i}", namespace)
        try:
            client.list_chunk_size = 2  # force multiple pages
            objs, rv = client.list("Pod")
            names = {
                o.metadata.name
                for o in objs
                if o.metadata.namespace == namespace
            }
            assert {f"page-{i}" for i in range(5)} <= names
            assert rv  # the first page's collection version
        finally:
            client.list_chunk_size = type(client).list_chunk_size
            for i in range(5):
                client.delete("Pod", namespace, f"page-{i}")


class TestInformerMirror:
    """The property the whole control plane rests on: after any write
    sequence plus quiescence, KubeStore's mirror == server state."""

    def test_crud_converges_through_watch(self, client, namespace):
        store = KubeStore(client, watch_kinds=("Pod",))
        try:
            for i in range(4):
                create_pod(client, f"m-{i}", namespace)
            # filter to this test's m-* prefix: the namespace is shared
            # module-scoped and a prior test's pods may still be
            # Terminating (real deletes are asynchronous)
            assert wait_until(
                lambda: {
                    o.metadata.name
                    for o in store.list("Pod", namespace=namespace)
                    if o.metadata.name.startswith("m-")
                }
                == {f"m-{i}" for i in range(4)}
            ), "mirror never converged on creates"
            client.delete("Pod", namespace, "m-0")
            client.delete("Pod", namespace, "m-1")
            # a real apiserver deletes pods asynchronously (grace
            # period, finalizers); the mirror must follow to whatever
            # the server settles on
            def server_equals_mirror():
                server = {
                    o.metadata.name
                    for o in client.list("Pod")[0]
                    if o.metadata.namespace == namespace
                    and o.metadata.name.startswith("m-")
                }
                mirror = {
                    o.metadata.name
                    for o in store.list("Pod", namespace=namespace)
                    if o.metadata.name.startswith("m-")
                }
                return server == mirror and "m-0" not in mirror
            assert wait_until(server_equals_mirror, timeout=60.0), (
                "mirror diverged from server after deletes"
            )
        finally:
            store.close()
            for i in range(2, 4):
                try:
                    client.delete("Pod", namespace, f"m-{i}")
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass


class TestStatusPatch:
    """Merge-patch on the status subresource: the write path every
    reconcile uses (GenericController analog)."""

    def test_status_merge_patch_round_trips(self, client, namespace):
        create_pod(client, "status-pod", namespace)
        try:
            live = client.get("Pod", namespace, "status-pod")
            live.status.phase = "Running"
            client.patch_status(live)
            fetched = client.get("Pod", namespace, "status-pod")
            assert fetched.status.phase == "Running"
        finally:
            client.delete("Pod", namespace, "status-pod")


class TestWatchRecovery:
    """API Concepts '410 Gone responses': a watch from an ancient
    resourceVersion must never wedge the informer — either the server
    still serves the history (uncompacted) or it signals too-old and
    the relist path recovers; the mirror converges either way."""

    def test_ancient_rv_watch_surfaces_or_replays(self, client, namespace):
        """Drive client.watch from resourceVersion=1 directly: a real
        apiserver either replays history (fresh etcd, rv 1 retained) or
        emits the in-stream 410 ERROR event, which KubeClient must
        surface as ConflictError (KubeStore's relist trigger) — never a
        hang or an unclassified crash."""
        import threading

        from karpenter_tpu.store import ConflictError

        create_pod(client, "old-rv", namespace)
        try:
            events = []
            stopped = threading.Event()
            short = _client(timeout=10.0)
            try:
                # the stream idles out at `timeout` if history replays
                short.watch(
                    "Pod", "1",
                    lambda etype, obj: (
                        events.append(etype), stopped.set()
                    ),
                    stopped,
                )
                replayed = True  # uncompacted: rv 1 was served
            except ConflictError:
                replayed = False  # the documented 410 path
            # both outcomes are legal; the forbidden ones (hang, raw
            # HTTPError) failed the call above
            assert replayed or not events

            # and the production informer converges regardless of how
            # old the collection's history is
            store = KubeStore(
                client, watch_kinds=("Pod",), resync_backoff=0.2
            )
            try:
                assert wait_until(
                    lambda: any(
                        o.metadata.name == "old-rv"
                        for o in store.list("Pod", namespace=namespace)
                    )
                )
            finally:
                store.close()
        finally:
            client.delete("Pod", namespace, "old-rv")


class TestScaleTargetDiscovery:
    """Arbitrary scale-target resolution against a genuine apiserver
    (reference: autoscaler.go:196-237): resolve a built-in kind the
    framework does not model via /apis discovery and drive its /scale
    subresource — GET and PUT — end to end."""

    def test_deployment_scale_round_trips(self, client, namespace):
        client._request(
            "POST",
            f"apis/apps/v1/namespaces/{namespace}/deployments",
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "scale-disc", "namespace": namespace},
                "spec": {
                    "replicas": 2,
                    "selector": {
                        "matchLabels": {"app": "scale-disc"}
                    },
                    "template": {
                        "metadata": {"labels": {"app": "scale-disc"}},
                        "spec": {
                            "nodeSelector": {
                                "karpenter-conformance/no-such": "node"
                            },
                            "containers": [
                                {
                                    "name": "main",
                                    "image": (
                                        "registry.k8s.io/pause:3.9"
                                    ),
                                }
                            ],
                        },
                    },
                },
            },
        )
        try:
            # discovery with the ref's apiVersion (the production path)
            assert client.resolve_kind("Deployment", "apps/v1") == (
                "apis/apps/v1", "deployments", True
            )
            # and blind discovery (walks /apis groups)
            fresh = _client()
            assert fresh.resolve_kind("Deployment") == (
                "apis/apps/v1", "deployments", True
            )
            scale = client.get_scale(
                "Deployment", namespace, "scale-disc",
                api_version="apps/v1",
            )
            assert scale.spec_replicas == 2
            scale.spec_replicas = 4
            client.update_scale(
                "Deployment", scale, api_version="apps/v1"
            )
            assert wait_until(
                lambda: client.get_scale(
                    "Deployment", namespace, "scale-disc",
                    api_version="apps/v1",
                ).spec_replicas == 4
            )
        finally:
            client._request(
                "DELETE",
                f"apis/apps/v1/namespaces/{namespace}"
                "/deployments/scale-disc",
            )
