"""L7 wiring: leader election, /metrics endpoint, CLI entry point.

reference: cmd/controller/main.go:40-77 (leader-elected manager, metrics
:8080) and the lease RBAC in config/rbac/role.yaml:62-71.
"""

import urllib.request

import pytest

from karpenter_tpu.__main__ import main as cli_main
from karpenter_tpu.__main__ import parse_args
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import MetricsServer, solver_trace
from karpenter_tpu.store import Store


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class TestLeaderElection:
    def test_first_candidate_acquires(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, identity="a", clock=clock)
        assert a.try_acquire()
        assert a.is_leader()

    def test_second_candidate_blocked_until_expiry(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, identity="a", clock=clock, lease_duration=15)
        b = LeaderElector(store, identity="b", clock=clock, lease_duration=15)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert not b.is_leader()
        # a keeps renewing: b stays out
        clock.advance(10)
        assert a.try_acquire()
        clock.advance(10)
        assert not b.try_acquire()
        # a dies (stops renewing): b takes over after expiry
        clock.advance(16)
        assert b.try_acquire()
        assert b.is_leader()
        assert not a.is_leader()
        # and a cannot renew its way back in while b holds
        assert not a.try_acquire()

    def test_renew_is_throttled_while_fresh(self):
        """Holding the lease must not rewrite it every tick — writes churn
        the store bus; renew only after a third of the lease elapses."""
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, identity="a", clock=clock, lease_duration=15)
        assert a.try_acquire()
        rv = store.get("Lease", a.namespace, a.name).metadata.resource_version
        clock.advance(1)
        assert a.try_acquire()  # fresh: no write
        assert (
            store.get("Lease", a.namespace, a.name).metadata.resource_version
            == rv
        )
        clock.advance(5)  # past lease_duration/3 since renew_time
        assert a.try_acquire()  # stale enough: renews
        assert (
            store.get("Lease", a.namespace, a.name).metadata.resource_version
            != rv
        )

    def test_leadership_lapses_without_renewal(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, identity="a", clock=clock, lease_duration=15)
        assert a.try_acquire()
        clock.advance(16)
        assert not a.is_leader()


class TestMetricsEndpoint:
    def test_serves_prometheus_text_and_health(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set(
            name="q", namespace="default", value=41.0
        )
        server = MetricsServer(registry, port=0, host="127.0.0.1")
        port = server.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "karpenter_queue_length" in body
            assert 'name="q"' in body
            assert "41" in body
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ).read()
            assert health == b"ok"
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5
                )
        finally:
            server.stop()


class TestSolverTrace:
    def test_trace_is_transparent(self):
        with solver_trace("binpack"):
            x = 2 + 2
        assert x == 4


class TestCLI:
    def test_flag_defaults_match_reference(self):
        args = parse_args([])
        assert args.metrics_port == 8080
        assert args.prometheus_uri is None
        assert args.leader_elect is True
        assert not args.verbose

    def test_profile_production_preset(self):
        # Without a profile the resolved defaults are the historical
        # ones: tick-paced loop, cold first compile, 1s objective.
        args = parse_args([])
        assert args.profile is None
        assert args.event_driven is False
        assert args.prewarm_compile is False
        assert args.selfslo_objective == 1.0

        # The production profile flips the event-driven plane and the
        # compile pre-warm on and tightens the self-SLO objective to
        # the sub-second 0.5 histogram bucket bound.
        args = parse_args(["--profile", "production"])
        assert args.event_driven is True
        assert args.prewarm_compile is True
        assert args.selfslo_objective == 0.5

    def test_profile_explicit_flags_win(self):
        args = parse_args(["--profile", "production", "--no-event-driven"])
        assert args.event_driven is False
        assert args.prewarm_compile is True
        assert args.selfslo_objective == 0.5

        args = parse_args(
            ["--profile", "production", "--selfslo-objective", "2.5"]
        )
        assert args.selfslo_objective == 2.5
        assert args.event_driven is True

        args = parse_args(["--profile", "production", "--no-prewarm-compile"])
        assert args.prewarm_compile is False

        # Explicit enablement without a profile still works and does
        # not drag the other preset values along.
        args = parse_args(["--event-driven"])
        assert args.event_driven is True
        assert args.prewarm_compile is False
        assert args.selfslo_objective == 1.0

    def test_main_runs_and_exits(self, capsys):
        rc = cli_main(
            [
                "--duration",
                "0.3",
                "--tick",
                "0.05",
                "--metrics-port",
                "0",
                "--no-leader-elect",
            ]
        )
        assert rc == 0

    def test_main_with_leader_election(self):
        rc = cli_main(
            ["--duration", "0.2", "--tick", "0.05", "--metrics-port", "0"]
        )
        assert rc == 0


class TestObservabilityFixes:
    def test_solver_trace_propagates_exceptions(self):
        with pytest.raises(ValueError, match="the real error"):
            with solver_trace("x"):
                raise ValueError("the real error")

    def test_metrics_path_with_query_string(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set(
            name="q", namespace="default", value=1.0
        )
        server = MetricsServer(registry, port=0, host="127.0.0.1")
        port = server.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prometheus",
                timeout=5,
            ).read().decode()
            assert "karpenter_queue_length" in body
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz?ready=1", timeout=5
            ).read()
            assert ok == b"ok"
        finally:
            server.stop()


class TestRealClusterModeCLI:
    def test_main_against_fake_apiserver_converges(self):
        """The FULL binary in real-cluster mode: `--apiserver` against the
        protocol-faithful fake apiserver — CRDs written upstream are
        mirrored in, reconciled, and their status/scale written back
        through the REST path (the deployment mode config/ ships)."""
        import json
        import urllib.request

        from fake_apiserver import FakeApiServer

        server = FakeApiServer()
        server.start()
        try:
            base = server.url

            def post(kind_path, manifest):
                req = urllib.request.Request(
                    f"{base}{kind_path}",
                    data=json.dumps(manifest).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())

            post(
                "/apis/autoscaling.karpenter.sh/v1alpha1/namespaces/"
                "default/scalablenodegroups",
                {
                    "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                    "kind": "ScalableNodeGroup",
                    "metadata": {"name": "g", "namespace": "default"},
                    "spec": {"replicas": 2, "type": "FakeNodeGroup",
                             "id": "g"},
                },
            )
            rc = cli_main(
                [
                    "--apiserver", base,
                    "--kube-insecure",
                    "--cloud-provider", "fake",
                    "--duration", "2.0",
                    "--tick", "0.05",
                    "--metrics-port", "0",
                    "--no-leader-elect",
                ]
            )
            assert rc == 0
            with urllib.request.urlopen(
                f"{base}/apis/autoscaling.karpenter.sh/v1alpha1/"
                "namespaces/default/scalablenodegroups/g"
            ) as resp:
                obj = json.loads(resp.read())
            # the integration contract under test is the REST round trip:
            # the CLI mirrored the upstream CRD in, reconciled it, and
            # PATCHed status back. (Active is legitimately False here —
            # the CLI's own fake provider has no replicas seeded for this
            # group — so assert the loop, not provider configuration.)
            conditions = {
                c["type"]: c["status"]
                for c in obj.get("status", {}).get("conditions", [])
            }
            assert conditions, obj  # status written upstream
            assert "Active" in conditions and "Stabilized" in conditions
        finally:
            server.stop()
