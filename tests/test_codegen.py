"""Deployment/config layer: generated CRDs match the API types, manifests
parse, kustomization references exist.

reference: the codegen gate in `make verify` (Makefile:37-53 controller-gen
output must be committed) — same posture here: config/crd/*.yaml is
generated from the dataclasses by karpenter_tpu.codegen and committed;
drift fails this test.
"""

import glob
import os

import yaml

from karpenter_tpu.codegen import CRD_KINDS, GROUP, crd_manifest, crd_yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCrdGeneration:
    def test_committed_crds_match_codegen(self):
        for kind, info in CRD_KINDS.items():
            path = os.path.join(
                REPO, "config", "crd", f"{GROUP}_{info['plural']}.yaml"
            )
            with open(path) as f:
                committed = f.read()
            assert committed == crd_yaml(kind), (
                f"{path} is stale — run `make codegen`"
            )

    def test_chart_crds_match_codegen(self):
        """The Helm chart installs CRDs via the crds/ convention; its
        copies are codegen outputs and must equal config/crd's (a chart
        that claims 'installs the three CRDs' but drifts — or lacks them
        entirely, the bug this pins — ships a controller with no API)."""
        for kind, info in CRD_KINDS.items():
            path = os.path.join(
                REPO,
                "charts",
                "karpenter-tpu",
                "crds",
                f"{GROUP}_{info['plural']}.yaml",
            )
            with open(path) as f:
                committed = f.read()
            assert committed == crd_yaml(kind), (
                f"{path} is stale — run `make codegen`"
            )

    def test_committed_api_docs_match_codegen(self):
        """docs/API.md is generated (make docs); committed == regenerated,
        same freshness contract as the CRDs."""
        import os

        from karpenter_tpu.codegen import api_docs_markdown

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "docs", "API.md")) as f:
            committed = f.read()
        assert committed == api_docs_markdown()

    def test_scale_subresource_on_scalablenodegroup(self):
        # reference: the kubebuilder scale marker, scalablenodegroup.go:51 —
        # this is what lets any HorizontalAutoscaler target the group
        crd = crd_manifest("ScalableNodeGroup")
        sub = crd["spec"]["versions"][0]["subresources"]
        assert sub["scale"] == {
            "specReplicasPath": ".spec.replicas",
            "statusReplicasPath": ".status.replicas",
        }
        assert sub["status"] == {}

    def test_schema_covers_spec_fields(self):
        crd = crd_manifest("HorizontalAutoscaler")
        spec = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]["spec"]["properties"]
        assert set(spec) == {
            "scaleTargetRef",
            "minReplicas",
            "maxReplicas",
            "metrics",
            "behavior",
        }
        behavior = spec["behavior"]["properties"]
        assert set(behavior) == {"scaleUp", "scaleDown", "forecast", "slo"}
        window = behavior["scaleUp"]["properties"][
            "stabilizationWindowSeconds"
        ]
        assert window == {"type": "integer"}
        forecast = behavior["forecast"]["properties"]
        assert forecast["horizonSeconds"] == {"type": "number"}
        assert forecast["minSamples"] == {"type": "integer"}
        slo = behavior["slo"]["properties"]
        assert slo["violationCostWeight"] == {"type": "number"}
        assert slo["maxHourlyCost"] == {"type": "number"}

    def test_schema_covers_warm_pool(self):
        crd = crd_manifest("ScalableNodeGroup")
        spec = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]["spec"]["properties"]
        warm = spec["warmPool"]["properties"]
        assert warm["minWarm"] == {"type": "integer"}
        assert warm["maxWarm"] == {"type": "integer"}

    def test_metric_target_values_are_numbers(self):
        # design departure from the reference: target values are plain
        # numbers (device-kernel floats), not resource.Quantity strings
        crd = crd_manifest("HorizontalAutoscaler")
        target = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]["spec"]["properties"]["metrics"]["items"]["properties"][
            "prometheus"
        ]["properties"]["target"]["properties"]
        assert target["value"] == {"type": "number"}
        assert target["averageUtilization"] == {"type": "integer"}

    def test_quantity_maps_to_string_schema(self):
        from karpenter_tpu.codegen import schema_for_type
        from karpenter_tpu.utils.quantity import Quantity

        assert schema_for_type(Quantity) == {"type": "string"}

    def test_one_of_spec_on_metricsproducer(self):
        crd = crd_manifest("MetricsProducer")
        spec = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]["spec"]["properties"]
        assert set(spec) == {
            "pendingCapacity",
            "reservedCapacity",
            "queue",
            "scheduleSpec",
        }


class TestManifestTree:
    def _docs(self, relpath):
        with open(os.path.join(REPO, relpath)) as f:
            return [d for d in yaml.safe_load_all(f) if d is not None]

    def test_all_config_manifests_parse(self):
        paths = glob.glob(os.path.join(REPO, "config", "**", "*.yaml"),
                          recursive=True)
        assert len(paths) >= 7
        for path in paths:
            docs = self._docs(os.path.relpath(path, REPO))
            assert docs, f"{path} is empty"

    def test_kustomization_resources_exist(self):
        (kustomization,) = self._docs("config/kustomization.yaml")
        for rel in kustomization["resources"]:
            assert os.path.exists(os.path.join(REPO, "config", rel)), rel

    def test_deployment_wires_solver_sidecar(self):
        docs = self._docs("config/manager/manager.yaml")
        deployment = next(d for d in docs if d["kind"] == "Deployment")
        containers = deployment["spec"]["template"]["spec"]["containers"]
        names = {c["name"] for c in containers}
        assert names == {"controller", "solver"}
        controller = next(c for c in containers if c["name"] == "controller")
        assert any("--solver-uri" in a for a in controller["args"])
        solver = next(c for c in containers if c["name"] == "solver")
        assert solver["resources"]["limits"]["google.com/tpu"] == 1

    def test_rbac_grants_scale_on_all_groups(self):
        # reference: config/rbac/role.yaml:33-41 — the autoscaler can write
        # the scale subresource of ANY kind a scaleTargetRef names
        docs = self._docs("config/rbac/role.yaml")
        role = next(d for d in docs if d["kind"] == "ClusterRole")
        assert any(
            rule["resources"] == ["*/scale"]
            and rule["apiGroups"] == ["*"]
            for rule in role["rules"]
        )

    def test_release_manifest_pinned_and_fresh(self):
        docs = self._docs("releases/manifest.yaml")
        kinds = [d["kind"] for d in docs]
        assert kinds.count("CustomResourceDefinition") == 4
        assert "Deployment" in kinds and "ClusterRole" in kinds
        # the pinned CRDs must equal codegen output (same no-drift gate)
        crds = {
            d["metadata"]["name"]: d
            for d in docs
            if d["kind"] == "CustomResourceDefinition"
        }
        for kind, info in CRD_KINDS.items():
            assert crds[f"{info['plural']}.{GROUP}"] == crd_manifest(kind)
