"""Solver sidecar: codec roundtrip, gRPC server/client over loopback, and
the producer path routed through a remote solver.

The sidecar is the BASELINE.json north-star process split (control plane ->
gRPC -> JAX solver); these tests run server and client in one process over
an ephemeral loopback port.
"""

import numpy as np
import pytest

from karpenter_tpu.ops.binpack import BinPackInputs, binpack
from karpenter_tpu.ops.decision import DecisionInputs, decide_jit
from karpenter_tpu.sidecar import SolverClient, SolverServer, codec

from test_binpack import make_inputs


@pytest.fixture(scope="module")
def server():
    s = SolverServer(port=0, host="127.0.0.1")
    port = s.start()
    yield f"127.0.0.1:{port}"
    s.stop()


@pytest.fixture(scope="module")
def client(server):
    with SolverClient(server) as c:
        yield c


class TestCodec:
    def test_roundtrip_arrays(self):
        arrays = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.asarray([True, False, True]),
            "scalar": np.asarray(7, np.int32),
        }
        packed = codec.pack(arrays, meta={"k": "v"})
        out, meta = codec.unpack(packed)
        assert meta == {"k": "v"}
        for name, arr in arrays.items():
            np.testing.assert_array_equal(out[name], arr)
            assert out[name].dtype == arr.dtype
            assert out[name].shape == arr.shape  # 0-d stays 0-d

    def test_roundtrip_dataclass(self):
        inputs = make_inputs(
            pod_requests=[[1, 1], [3, 1]], group_allocatable=[[4, 4]]
        )
        back, _ = codec.unpack_dataclass(
            BinPackInputs, codec.pack_dataclass(inputs)
        )
        np.testing.assert_array_equal(
            np.asarray(back.pod_requests), np.asarray(inputs.pod_requests)
        )
        np.testing.assert_array_equal(
            np.asarray(back.group_taints), np.asarray(inputs.group_taints)
        )

    def test_tensor_set_mismatch_rejected(self):
        packed = codec.pack({"bogus": np.zeros(3)})
        with pytest.raises(ValueError):
            codec.unpack_dataclass(BinPackInputs, packed)


class TestSolverRPC:
    def test_health(self, client):
        ok, meta = client.health()
        assert ok
        assert "backend" in meta

    def test_solve_matches_inprocess(self, client):
        inputs = make_inputs(
            pod_requests=[[1, 1], [3, 1], [9, 9]],
            group_allocatable=[[2, 2], [4, 4]],
        )
        local = binpack(inputs, buckets=16)
        remote = client.solve(inputs, buckets=16)
        np.testing.assert_array_equal(
            np.asarray(remote.assigned), np.asarray(local.assigned)
        )
        np.testing.assert_array_equal(
            np.asarray(remote.nodes_needed), np.asarray(local.nodes_needed)
        )
        assert int(remote.unschedulable) == int(local.unschedulable)

    def test_decide_matches_inprocess(self, client):
        n, m = 4, 2
        inputs = DecisionInputs(
            metric_value=np.asarray([[0.85, 0], [41, 0], [1, 0], [5, 0]], np.float32),
            target_value=np.asarray([[0.6, 1], [4, 1], [2, 1], [5, 1]], np.float32),
            target_type=np.full((n, m), 2, np.int32),
            metric_valid=np.asarray([[True, False]] * n),
            spec_replicas=np.asarray([5, 1, 3, 2], np.int32),
            status_replicas=np.asarray([5, 1, 3, 2], np.int32),
            min_replicas=np.zeros(n, np.int32),
            max_replicas=np.full(n, 100, np.int32),
            up_window=np.zeros(n, np.int32),
            down_window=np.zeros(n, np.int32),
            up_policy=np.zeros(n, np.int32),
            down_policy=np.zeros(n, np.int32),
            last_scale_time=np.zeros(n, np.float32),
            has_last_scale=np.zeros(n, bool),
            now=np.asarray(1000.0, np.float32),
            up_ptype=np.zeros((n, 1), np.int32),
            up_pvalue=np.asarray([[4]] * n, np.int32),
            up_pperiod=np.full((n, 1), 60, np.int32),
            up_pvalid=np.asarray([[True], [False], [False], [False]]),
            down_ptype=np.zeros((n, 1), np.int32),
            down_pvalue=np.ones((n, 1), np.int32),
            down_pperiod=np.full((n, 1), 60, np.int32),
            down_pvalid=np.zeros((n, 1), bool),
        )
        local = decide_jit(inputs)
        remote = client.decide(inputs)
        np.testing.assert_array_equal(
            np.asarray(remote.desired), np.asarray(local.desired)
        )
        np.testing.assert_array_equal(
            np.asarray(remote.able_to_scale), np.asarray(local.able_to_scale)
        )

    def test_error_surfaces_as_status(self, client, server):
        import grpc

        # a malformed request must produce INTERNAL with a message, not a
        # hung/dead channel
        with SolverClient(server) as c:
            with pytest.raises(grpc.RpcError) as e:
                c._solve(b"\x00" * 4, timeout=5.0)
            assert e.value.code() == grpc.StatusCode.INTERNAL


class TestProducerThroughSidecar:
    def test_pending_capacity_via_remote_solver(self, client):
        """The full producer path with the sidecar at the Algorithm seam."""
        from karpenter_tpu.api.core import (
            Node,
            NodeCondition,
            NodeSpec,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
            resource_list,
        )
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            PendingCapacitySpec,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            solve_pending,
        )
        from karpenter_tpu.store import Store

        store = Store()
        store.create(
            Node(
                metadata=ObjectMeta(
                    name="n1", labels={"pool": "a"}
                ),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable=resource_list(cpu="8", memory="16Gi", pods="16"),
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
        store.create(
            Pod(
                metadata=ObjectMeta(name="p1"),
                spec=PodSpec(),  # pending, no node
            )
        )
        mp = MetricsProducer(
            metadata=ObjectMeta(name="pending"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={"pool": "a"}
                )
            ),
        )
        store.create(mp)
        registry = GaugeRegistry()
        solve_pending(store, [mp], registry, solver=client.solve)
        status = mp.status.pending_capacity
        assert status is not None
        assert status.pending_pods == 1
        assert status.additional_nodes_needed >= 1


class TestDecideSplit:
    def test_control_plane_decides_through_sidecar(self):
        """With --solver-uri the decision kernel rides the gRPC split too:
        the full HA pipeline (metric read -> remote decide -> scale write)
        must produce the canonical 85%/60%/5 -> 8 result with the device
        math in the sidecar process."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.horizontalautoscaler import (
            CrossVersionObjectReference,
            HorizontalAutoscaler,
            HorizontalAutoscalerSpec,
            Metric,
            MetricTarget,
            PrometheusMetricSource,
        )
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options
        from karpenter_tpu.sidecar.server import SolverServer

        server = SolverServer(port=0)
        port = server.start()
        try:
            provider = FakeFactory()
            provider.node_replicas["g"] = 5
            rt = KarpenterRuntime(
                Options(
                    cloud_provider="fake",
                    solver_uri=f"127.0.0.1:{port}",
                ),
                cloud_provider_factory=provider,
            )
            # the shared solve service fronts the sidecar client: the
            # autoscaler submits through the service, whose decider seam
            # is the remote decide — device math stays out of process
            assert rt.batch_autoscaler.decider == rt.solver_service.decide
            assert rt.solver_service._decider == rt.solver_client.decide
            assert (
                rt.solver_service.device_solver == rt.solver_client.solve
            )
            gauge = rt.registry.register("reserved_capacity",
                                         "cpu_utilization")
            gauge.set("g", "default", 0.85)
            rt.store.create(ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=5, type="FakeNodeGroup", id="g")))
            rt.store.create(HorizontalAutoscaler(
                metadata=ObjectMeta(name="ha"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name="g"),
                    min_replicas=3, max_replicas=23,
                    metrics=[Metric(prometheus=PrometheusMetricSource(
                        query='karpenter_reserved_capacity_cpu_utilization{name="g"}',
                        target=MetricTarget(type="Utilization", value=60)))])))
            rt.manager.reconcile_all()
            ha = rt.store.get("HorizontalAutoscaler", "default", "ha")
            assert ha.status.desired_replicas == 8
            rt.close()  # release the gRPC channel before the server stops
        finally:
            server.stop()


class TestCompileCache:
    def test_configure_sets_jax_flags(self, tmp_path):
        """--compile-cache-dir wires JAX's persistent compilation cache
        (restart survival for the 20-40s TPU solver compiles); empty
        stays disabled."""
        import jax

        from karpenter_tpu.utils.backend import configure_compile_cache

        assert configure_compile_cache("") is False
        cache = tmp_path / "xla-cache"
        assert configure_compile_cache(str(cache)) is True
        try:
            assert jax.config.jax_compilation_cache_dir == str(cache)
            assert (
                jax.config.jax_persistent_cache_min_compile_time_secs == 1.0
            )
            # functional: with the write threshold floored, a fresh jit
            # lands an entry in the directory (proves the wiring, not
            # just the flag)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0
            )
            import numpy as np

            fn = jax.jit(lambda x: x * 2.0 + 1.0)
            fn(np.arange(8, dtype=np.float32)).block_until_ready()
            assert any(cache.iterdir()), "no cache entry written"
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
