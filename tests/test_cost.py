"""Cost subsystem tests (docs/cost.md).

The load-bearing pins:

  * PARITY — the multi-objective kernel's XLA and numpy paths produce
    bit-identical outputs on every field over randomized fleets
    (ops/cost.py module docstring contract).
  * WIRE-COMPAT — absent/zero cost operands reproduce today's decisions
    bit-identically: slo-less rows pass through exactly, and a
    weight-0/uncapped row chooses its base desired exactly.
  * the CostEngine's never-block contract and zero-overhead opt-out;
  * warm pools actuating spec.replicas + warm through the ordinary
    ScalableNodeGroup controller door;
  * karpenter_cost_* / karpenter_warmpool_* passing the promtool-style
    exposition lint;
  * the non-slow batched-vs-per-HA regression guard (`make bench-cost`
    publishes the full numbers).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np
import pytest

from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.api.horizontalautoscaler import (
    Behavior,
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
    SLOSpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
    WarmPoolSpec,
)
from karpenter_tpu.cost import (
    CostEngine,
    CostModel,
    HOURLY_COST_ANNOTATION,
    INSTANCE_TYPE_ANNOTATION,
    WarmPoolEngine,
)
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import cost as CK
from karpenter_tpu.store import Store

from test_observability import _lint_exposition


def random_inputs(seed: int, n: int = 64, m: int = 3) -> CK.CostInputs:
    rng = np.random.RandomState(seed)
    base = rng.randint(0, 100, n).astype(np.int32)
    return CK.CostInputs(
        base_desired=base,
        min_replicas=rng.randint(0, 5, n).astype(np.int32),
        max_replicas=(base + rng.randint(0, 300, n)).astype(np.int32),
        unit_cost=rng.choice([0.0, 0.07, 0.3, 1.7, 12.5], n).astype(
            np.float32
        ),
        slo_weight=rng.choice([0.0, 1.0, 50.0, 333.3], n).astype(
            np.float32
        ),
        max_hourly_cost=rng.choice([0.0, 2.0, 10.0, 55.5], n).astype(
            np.float32
        ),
        slo_valid=rng.rand(n) > 0.3,
        slo_target=rng.uniform(0.5, 10, (n, m)).astype(np.float32),
        demand_mu=rng.uniform(0, 500, (n, m)).astype(np.float32),
        demand_sigma=rng.choice([0.0, 3.0, 25.0], (n, m)).astype(
            np.float32
        ),
        demand_valid=rng.rand(n, m) > 0.2,
    )


def make_inputs(**overrides) -> CK.CostInputs:
    """One-row inputs with benign defaults, field-overridable."""
    fields = dict(
        base_desired=np.asarray([10], np.int32),
        min_replicas=np.asarray([0], np.int32),
        max_replicas=np.asarray([1000], np.int32),
        unit_cost=np.asarray([1.0], np.float32),
        slo_weight=np.asarray([0.0], np.float32),
        max_hourly_cost=np.asarray([0.0], np.float32),
        slo_valid=np.asarray([True]),
        slo_target=np.asarray([[4.0]], np.float32),
        demand_mu=np.asarray([[40.0]], np.float32),
        demand_sigma=np.asarray([[0.0]], np.float32),
        demand_valid=np.asarray([[True]]),
    )
    fields.update(overrides)
    return CK.CostInputs(**fields)


class TestKernelParity:
    def test_xla_matches_numpy_bitwise_over_random_fleets(self):
        """The parity contract: every output field of cost_jit and
        cost_numpy is bit-identical across randomized fleets and
        shapes."""
        for seed in range(8):
            for n, m in ((64, 3), (256, 1), (8, 5)):
                inputs = random_inputs(seed, n, m)
                dev = CK.cost_jit(inputs)
                host = CK.cost_numpy(inputs)
                for f in dataclasses.fields(CK.CostOutputs):
                    a = np.asarray(getattr(dev, f.name))
                    b = np.asarray(getattr(host, f.name))
                    assert np.array_equal(a, b), (
                        f"seed={seed} n={n} m={m}: {f.name} diverged"
                    )

    def test_invalid_rows_pass_through_exactly(self):
        """Wire-compat: slo_valid False reproduces the base decision
        bit for bit — an SLO-free fleet is untouched."""
        inputs = random_inputs(1)
        inputs = dataclasses.replace(
            inputs, slo_valid=np.zeros(64, bool)
        )
        out = CK.cost_jit(inputs)
        assert np.array_equal(
            np.asarray(out.desired), np.asarray(inputs.base_desired)
        )
        assert not np.asarray(out.slo_raised).any()
        assert not np.asarray(out.cost_limited).any()

    def test_zero_weight_uncapped_keeps_base(self):
        """Wire-compat: a valid row with violationCostWeight 0 and no
        budget scores minimal at candidate 0 — the base decision,
        exactly (argmin ties break first)."""
        out = CK.cost_jit(make_inputs(
            demand_mu=np.asarray([[400.0]], np.float32),  # underwater
        ))
        assert int(out.desired[0]) == 10
        assert not bool(out.slo_raised[0])

    def test_risk_weight_buys_replicas(self):
        """A heavy violation weight raises desired toward the count
        whose SLO capacity covers the one-sigma demand."""
        out = CK.cost_jit(make_inputs(
            slo_weight=np.asarray([100.0], np.float32),
            demand_mu=np.asarray([[56.0]], np.float32),  # needs 14
        ))
        assert int(out.desired[0]) == 14
        assert bool(out.slo_raised[0])
        assert float(out.violation_risk[0]) == 0.0

    def test_forecast_sigma_widens_the_buy(self):
        """The PR 5 forecast distribution as the risk input: sigma adds
        pessimism, so the same mu buys more replicas."""
        base = CK.cost_jit(make_inputs(
            slo_weight=np.asarray([100.0], np.float32),
            demand_mu=np.asarray([[48.0]], np.float32),
        ))
        widened = CK.cost_jit(make_inputs(
            slo_weight=np.asarray([100.0], np.float32),
            demand_mu=np.asarray([[48.0]], np.float32),
            demand_sigma=np.asarray([[8.0]], np.float32),
        ))
        assert int(widened.desired[0]) > int(base.desired[0])

    def test_budget_cap_trims_but_respects_min_replicas(self):
        out = CK.cost_jit(make_inputs(
            base_desired=np.asarray([20], np.int32),
            max_hourly_cost=np.asarray([8.0], np.float32),  # caps at 8
        ))
        assert int(out.desired[0]) == 8
        assert bool(out.cost_limited[0])
        floored = CK.cost_jit(make_inputs(
            base_desired=np.asarray([20], np.int32),
            min_replicas=np.asarray([12], np.int32),
            max_hourly_cost=np.asarray([8.0], np.float32),
        ))
        # the budget never takes a workload below its declared floor
        assert int(floored.desired[0]) == 12

    def test_headroom_reports_one_sigma_surplus(self):
        """The warm-pool sizing signal: replicas the pessimistic demand
        needs beyond the chosen count."""
        out = CK.cost_jit(make_inputs(
            demand_mu=np.asarray([[48.0]], np.float32),
            demand_sigma=np.asarray([[16.0]], np.float32),
        ))
        # needs ceil(64/4)=16, chose 10 (weight 0) -> headroom 6
        assert int(out.headroom[0]) == 6

    def test_expected_hourly_prices_the_choice(self):
        out = CK.cost_jit(make_inputs(
            unit_cost=np.asarray([0.5], np.float32),
        ))
        assert float(out.expected_hourly[0]) == pytest.approx(5.0)


class TestCostModel:
    def test_catalog_and_default(self):
        model = CostModel()
        assert model.on_demand("m5.large") == pytest.approx(0.096)
        assert model.on_demand("no-such-type") == 1.0
        assert model.on_demand(None) == 1.0

    def test_spot_tier_composes_with_capacity_labels(self):
        """The SAME spot labels the packing kernels steer on price the
        spot tier here (api/core.capacity_tier_of composition)."""
        model = CostModel()
        on_demand = model.node_cost(
            {"node.kubernetes.io/instance-type": "m5.large"}
        )
        spot = model.node_cost({
            "node.kubernetes.io/instance-type": "m5.large",
            "karpenter.sh/capacity-type": "spot",
        })
        assert spot == pytest.approx(on_demand * 0.35)

    def test_group_costs_is_columnar_over_profiles(self):
        model = CostModel()
        profiles = [
            ({}, {"node.kubernetes.io/instance-type": "m5.large"}, set()),
            ({}, {"karpenter.sh/capacity-type": "spot"}, set()),
            ({}, {}, set()),
        ]
        costs = model.group_costs(profiles)
        assert costs.dtype == np.float32
        assert costs.shape == (3,)
        assert costs[0] == pytest.approx(0.096)
        assert costs[1] == pytest.approx(0.35)
        assert costs[2] == pytest.approx(1.0)

    def test_unit_cost_annotation_overrides(self):
        model = CostModel()
        sng = ScalableNodeGroup(
            metadata=ObjectMeta(
                name="g", annotations={HOURLY_COST_ANNOTATION: "7.25"}
            ),
            spec=ScalableNodeGroupSpec(type="FakeNodeGroup", id="g"),
        )
        assert model.unit_cost(sng) == pytest.approx(7.25)
        sng.metadata.annotations = {
            INSTANCE_TYPE_ANNOTATION: "m5.xlarge"
        }
        assert model.unit_cost(sng) == pytest.approx(0.192)
        sng.spec.preemptible = True
        assert model.unit_cost(sng) == pytest.approx(0.192 * 0.35)

    def test_unparseable_override_falls_through(self):
        model = CostModel()
        sng = ScalableNodeGroup(
            metadata=ObjectMeta(
                name="g",
                annotations={HOURLY_COST_ANNOTATION: "not-a-price"},
            ),
            spec=ScalableNodeGroupSpec(type="FakeNodeGroup", id="g"),
        )
        assert model.unit_cost(sng) == 1.0

    def test_unit_cost_none_resource(self):
        assert CostModel().unit_cost(None) == 1.0


def _world(slo=None, queue=41.0, replicas=5, annotations=None):
    """(store, registry, batch-autoscaler world) around one SNG-backed
    queue HA — the chaos-suite shape, minus the runtime."""
    from karpenter_tpu.autoscaler import BatchAutoscaler
    from karpenter_tpu.metrics.clients import MetricsClientFactory

    store = Store()
    registry = GaugeRegistry()
    registry.register("queue", "length").set("q", "default", queue)
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="g", annotations=annotations or {}),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id="g"
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="g"
            ),
            min_replicas=1,
            max_replicas=1000,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
            behavior=Behavior(slo=slo),
        ),
    ))
    engine = CostEngine(store=store, registry=registry)
    autoscaler = BatchAutoscaler(
        MetricsClientFactory(registry=registry), store,
        cost_engine=engine,
    )
    return store, registry, engine, autoscaler


def _reconcile(store, autoscaler):
    ha = store.get("HorizontalAutoscaler", "default", "ha")
    errors = autoscaler.reconcile_batch([ha])
    error = errors[("default", "ha")]
    if error is not None:
        raise error
    return store.get_scale("ScalableNodeGroup", "default", "g")


class TestCostEngine:
    REACTIVE = 11  # queue 41 / AverageValue target 4 -> ceil

    def test_slo_free_fleet_is_bit_identical_and_zero_overhead(self):
        store, _registry, engine, autoscaler = _world(slo=None)
        calls = []
        engine.cost_fn = lambda inputs: calls.append(1)
        scale = _reconcile(store, autoscaler)
        assert scale.spec_replicas == self.REACTIVE
        assert calls == []  # no dispatch, no arrays — the opt-out

    def test_slo_risk_raises_desired(self):
        """sloTarget below the HPA target prices risk into extra
        replicas: 41 demand / 3-per-replica SLO needs 14."""
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, registry, _engine, autoscaler = _world(slo=slo)
        scale = _reconcile(store, autoscaler)
        assert scale.spec_replicas == 14
        assert registry.gauge("cost", "violation_risk").get(
            "ha", "default"
        ) == 0.0
        assert registry.gauge("cost", "expected_hourly").get(
            "ha", "default"
        ) == pytest.approx(14.0)  # default model: $1/replica-hour

    def test_max_hourly_cost_caps_desired(self):
        slo = SLOSpec(max_hourly_cost=8.0)
        store, _registry, _engine, autoscaler = _world(slo=slo)
        scale = _reconcile(store, autoscaler)
        assert scale.spec_replicas == 8  # floor(8 / $1)

    def test_unit_cost_prices_through_the_scale_target(self):
        """The SNG's cost annotations reach the kernel: a $2/replica
        group affords only 4 replicas under an $8 budget."""
        slo = SLOSpec(max_hourly_cost=8.0)
        store, _registry, _engine, autoscaler = _world(
            slo=slo, annotations={HOURLY_COST_ANNOTATION: "2.0"}
        )
        scale = _reconcile(store, autoscaler)
        assert scale.spec_replicas == 4

    def test_never_block_on_cost_failure(self):
        """Any cost_fn failure degrades to the base (cost-blind)
        decision and counts blind_total — the tick never fails."""
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, registry, engine, autoscaler = _world(slo=slo)

        def boom(inputs):
            raise RuntimeError("injected cost failure")

        engine.cost_fn = boom
        scale = _reconcile(store, autoscaler)
        assert scale.spec_replicas == self.REACTIVE
        assert registry.gauge("cost", "blind_total").get(
            "ha", "default"
        ) == 1.0

    def test_headroom_decays_for_vanished_targets(self):
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, registry, engine, autoscaler = _world(slo=slo)
        _reconcile(store, autoscaler)
        assert engine.headroom("default", "g") >= 0
        assert ("default", "ha") in engine._contrib
        assert registry.gauge("cost", "violation_risk").get(
            "ha", "default"
        ) is not None
        # the HA drops its slo spec: the next pass drops its headroom
        # entry AND its gauge series — a frozen pre-opt-out value would
        # mislead dashboards
        ha = store.get("HorizontalAutoscaler", "default", "ha")
        ha.spec.behavior.slo = None
        store.update(ha)
        _reconcile(store, autoscaler)
        assert engine.headroom("default", "g") == 0
        assert registry.gauge("cost", "violation_risk").get(
            "ha", "default"
        ) is None
        assert registry.gauge("cost", "expected_hourly").get(
            "ha", "default"
        ) is None

    def test_prune_drops_deleted_has_headroom(self):
        """A DELETED HA never appears in another pass — prune() must
        retire its headroom contribution or its group would hold
        risk-sized warm capacity forever."""
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, _registry, engine, autoscaler = _world(slo=slo)
        _reconcile(store, autoscaler)
        assert ("default", "ha") in engine._contrib
        engine.prune("default", "ha")
        assert engine.headroom("default", "g") == 0

    def test_refine_honors_decide_movement_bounds(self):
        """The candidate ladder must respect the decide kernel's
        per-tick movement bounds (up_ceiling/down_floor): an SLO raise
        converges at the declared scaleUp rate, never in one jump past
        it."""
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, _registry, _engine, autoscaler = _world(slo=slo)
        base = autoscaler.decider

        def capped(inputs):
            # a Pods:1/period scaleUp policy, as the decide kernel
            # models it: this tick moves at most +-1 from current spec,
            # and up_ceiling/down_floor report exactly that bound
            out = base(inputs)
            spec = np.asarray(inputs.spec_replicas, np.int32)
            ceiling = (spec + 1).astype(np.int32)
            floor = np.maximum(spec - 1, 0).astype(np.int32)
            return dataclasses.replace(
                out,
                desired=np.clip(
                    np.asarray(out.desired, np.int32), floor, ceiling
                ),
                up_ceiling=ceiling,
                down_floor=floor,
            )

        autoscaler.decider = capped
        # without the bound the SLO raise would go straight toward 14
        # (test_slo_risk_raises_desired); the refinement must instead
        # converge at the declared +1-per-tick rate
        assert _reconcile(store, autoscaler).spec_replicas == 6
        assert _reconcile(store, autoscaler).spec_replicas == 7

    def test_gauges_pass_exposition_lint(self):
        """Satellite pin: the new karpenter_cost_* series survive the
        promtool-style lint next to everything else."""
        slo = SLOSpec(target_value=3.0, violation_cost_weight=100.0)
        store, registry, _engine, autoscaler = _world(slo=slo)
        _reconcile(store, autoscaler)
        typed, series = _lint_exposition(registry.expose_text())
        names = {name for name, _labels, _v in series}
        assert "karpenter_cost_expected_hourly" in names
        assert "karpenter_cost_violation_risk" in names
        assert "karpenter_cost_adjusted_total" in names
        assert typed["karpenter_cost_adjusted_total"] == "counter"


class TestWarmPool:
    def _controller(self, headroom=0, registry=None):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.controllers import ScalableNodeGroupController

        provider = FakeFactory()
        provider.node_replicas["g"] = 3
        warmpool = WarmPoolEngine(
            headroom_source=lambda ns, name: headroom,
            registry=registry,
        )
        controller = ScalableNodeGroupController(
            provider, warmpool=warmpool, registry=registry
        )
        return provider, controller

    def _sng(self, warm_pool=None, replicas=3):
        return ScalableNodeGroup(
            metadata=ObjectMeta(name="g"),
            spec=ScalableNodeGroupSpec(
                replicas=replicas, type="FakeNodeGroup", id="g",
                warm_pool=warm_pool,
            ),
        )

    def test_warm_target_actuates_through_the_controller(self):
        provider, controller = self._controller()
        sng = self._sng(WarmPoolSpec(min_warm=2, max_warm=6))
        controller.reconcile(sng)
        assert provider.node_replicas["g"] == 5  # 3 desired + 2 warm
        assert sng.status.replicas == 3  # the pre-actuation observation

    def test_risk_headroom_grows_warm_within_bounds(self):
        provider, controller = self._controller(headroom=4)
        controller.reconcile(self._sng(WarmPoolSpec(2, 6)))
        assert provider.node_replicas["g"] == 7  # 3 + clip(4, [2,6])
        provider2, controller2 = self._controller(headroom=50)
        controller2.reconcile(self._sng(WarmPoolSpec(2, 6)))
        assert provider2.node_replicas["g"] == 9  # maxWarm caps at 6

    def test_no_warm_pool_is_byte_identical(self):
        provider, controller = self._controller(headroom=4)
        controller.reconcile(self._sng(warm_pool=None))
        assert provider.node_replicas["g"] == 3  # converged, no write

    def test_broken_risk_source_degrades_to_min_warm(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.controllers import ScalableNodeGroupController

        provider = FakeFactory()
        provider.node_replicas["g"] = 3

        def boom(ns, name):
            raise RuntimeError("risk source down")

        controller = ScalableNodeGroupController(
            provider, warmpool=WarmPoolEngine(headroom_source=boom)
        )
        controller.reconcile(self._sng(WarmPoolSpec(2, 6)))
        assert provider.node_replicas["g"] == 5  # minWarm floor held

    def test_status_replicas_excludes_warm_headroom(self):
        """status.replicas feeds the HPA's proportional math as current
        replicas — warm nodes counted there would ratchet
        Value/Utilization fleets up by the warm amount every tick. Only
        nodes beyond spec.replicas are warm: mid-transition everything
        observed up to spec is serving."""
        provider, controller = self._controller()
        provider.node_replicas["g"] = 5  # converged: 3 desired + 2 warm
        sng = self._sng(WarmPoolSpec(min_warm=2, max_warm=6))
        controller.reconcile(sng)
        assert sng.status.replicas == 3  # serving only, warm excluded
        # mid-scale-up (warm not yet provisioned): all observed serve
        provider2, controller2 = self._controller()
        provider2.node_replicas["g"] = 3
        sng2 = self._sng(WarmPoolSpec(min_warm=2, max_warm=6))
        controller2.reconcile(sng2)
        assert sng2.status.replicas == 3

    def test_warm_gauges_pass_exposition_lint(self):
        registry = GaugeRegistry()
        provider, controller = self._controller(
            headroom=4, registry=registry
        )
        controller.reconcile(self._sng(WarmPoolSpec(2, 6)))
        typed, series = _lint_exposition(registry.expose_text())
        names = {name for name, _labels, _v in series}
        assert "karpenter_warmpool_replicas" in names
        assert "karpenter_warmpool_risk_replicas" in names

    def test_on_deleted_drops_gauges(self):
        registry = GaugeRegistry()
        provider, controller = self._controller(
            headroom=1, registry=registry
        )
        sng = self._sng(WarmPoolSpec(1, 3))
        controller.reconcile(sng)
        assert registry.gauge("warmpool", "replicas").get(
            "g", "default"
        ) is not None
        controller.on_deleted(sng)
        assert registry.gauge("warmpool", "replicas").get(
            "g", "default"
        ) is None


class TestServiceSeam:
    def test_numpy_backend_serves_the_mirror(self):
        from karpenter_tpu.solver import SolverService

        service = SolverService(backend="numpy")
        try:
            inputs = random_inputs(3)
            out = service.cost(inputs)
            mirror = CK.cost_numpy(inputs)
            assert np.array_equal(
                np.asarray(out.desired), np.asarray(mirror.desired)
            )
            assert service.stats.cost_calls == 1
            assert service.stats.cost_dispatches == 0
        finally:
            service.close()

    def test_degraded_fsm_short_circuits_cost_blind(self):
        """A tripped backend FSM makes cost() fail fast (the caller
        goes cost-blind) instead of billing the sick device; a due
        probe rides the device path again."""
        from karpenter_tpu.solver.service import (
            CostUnavailable,
            DEGRADED,
            SolverService,
        )

        clock = {"now": 1000.0}
        service = SolverService(
            backend="xla", health_probe_interval_s=30.0,
            clock=lambda: clock["now"],
        )
        try:
            with service._health_lock:
                service._health = DEGRADED
                service._next_probe = clock["now"] + 30.0
            with pytest.raises(CostUnavailable):
                service.cost(random_inputs(0))
            assert service.stats.cost_errors == 1
            # probe due: the device path runs and recovery follows
            clock["now"] += 31.0
            out = service.cost(random_inputs(0))
            assert out is not None
            assert service.backend_health() == "healthy"
        finally:
            service.close()

    def test_device_failure_feeds_fsm_and_propagates(self):
        from karpenter_tpu import faults
        from karpenter_tpu.faults import FaultRegistry
        from karpenter_tpu.solver import SolverService

        service = SolverService(backend="xla", health_failure_threshold=2)
        try:
            with FaultRegistry(seed=1) as registry:
                registry.plan("cost.score", probability=1.0)
                for _ in range(2):
                    with pytest.raises(faults.FaultInjected):
                        service.cost(random_inputs(0))
            assert service.stats.fsm_trips == 1
            assert service.stats.cost_errors == 2
        finally:
            service.close()


class TestApiValidation:
    def test_slo_spec_bounds(self):
        SLOSpec(target_value=1.0, violation_cost_weight=5.0).validate()
        with pytest.raises(ValueError):
            SLOSpec(target_value=0.0).validate()
        with pytest.raises(ValueError):
            SLOSpec(violation_cost_weight=-1.0).validate()
        with pytest.raises(ValueError):
            SLOSpec(max_hourly_cost=-0.5).validate()

    def test_warm_pool_bounds(self):
        WarmPoolSpec(min_warm=0, max_warm=4).validate()
        with pytest.raises(ValueError):
            WarmPoolSpec(min_warm=-1, max_warm=4).validate()
        with pytest.raises(ValueError):
            WarmPoolSpec(min_warm=5, max_warm=4).validate()

    def test_ha_validate_reaches_slo(self):
        ha = HorizontalAutoscaler(
            spec=HorizontalAutoscalerSpec(
                max_replicas=10,
                behavior=Behavior(slo=SLOSpec(target_value=-2.0)),
            )
        )
        with pytest.raises(ValueError):
            ha.validate()

    def test_sng_validate_reaches_warm_pool(self):
        sng = ScalableNodeGroup(
            spec=ScalableNodeGroupSpec(
                type="FakeNodeGroup", id="g",
                warm_pool=WarmPoolSpec(min_warm=3, max_warm=1),
            )
        )
        with pytest.raises(ValueError):
            sng.validate()

    def test_specs_serialize_round_trip(self):
        from karpenter_tpu.api.serialization import from_dict, to_dict

        ha = HorizontalAutoscaler(
            metadata=ObjectMeta(name="ha"),
            spec=HorizontalAutoscalerSpec(
                max_replicas=10,
                behavior=Behavior(slo=SLOSpec(
                    target_value=3.0, violation_cost_weight=50.0,
                    max_hourly_cost=12.0,
                )),
            ),
        )
        doc = to_dict(ha)
        assert doc["spec"]["behavior"]["slo"]["violationCostWeight"] == 50.0
        back = from_dict(HorizontalAutoscaler, doc)
        assert back.spec.behavior.slo.max_hourly_cost == 12.0

        sng = ScalableNodeGroup(
            metadata=ObjectMeta(name="g"),
            spec=ScalableNodeGroupSpec(
                type="FakeNodeGroup", id="g",
                warm_pool=WarmPoolSpec(min_warm=1, max_warm=4),
            ),
        )
        doc = to_dict(sng)
        assert doc["spec"]["warmPool"]["minWarm"] == 1
        back = from_dict(ScalableNodeGroup, doc)
        assert back.spec.warm_pool.max_warm == 4


class TestRegressionGuard:
    def test_batched_refine_beats_per_ha_loop(self):
        """Non-slow guard for the bench-cost claim: one fleet dispatch
        must beat N single-row dispatches (generously — the published
        numbers live in docs/BENCHMARKS.md)."""
        import jax

        inputs = random_inputs(0, n=64, m=3)
        rows = [
            dataclasses.replace(
                inputs,
                **{
                    f.name: np.asarray(getattr(inputs, f.name))[i: i + 1]
                    for f in dataclasses.fields(inputs)
                },
            )
            for i in range(64)
        ]
        jax.block_until_ready(CK.cost_jit(inputs))  # warm both shapes
        jax.block_until_ready(CK.cost_jit(rows[0]))

        def best_of(fn, reps=3):
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return min(times)

        batched = best_of(
            lambda: jax.block_until_ready(CK.cost_jit(inputs))
        )
        sequential = best_of(
            lambda: [
                jax.block_until_ready(CK.cost_jit(row)) for row in rows
            ]
        )
        assert batched < sequential, (
            f"batched {batched * 1e3:.2f}ms not faster than per-HA "
            f"loop {sequential * 1e3:.2f}ms"
        )
