"""hack/smoke-manifest.py: the kind-smoke transform must track config/.

The smoke job (make kind-smoke, presubmit `smoke`) pipes the kustomize
output through this transform; if config/ grows something a bare kind
cluster cannot satisfy and the transform misses it, the smoke wedges in
CI. Pinning the transform against the LIVE config tree catches that at
unit speed."""

import importlib.util
import pathlib

import pytest

yaml = pytest.importorskip("yaml")

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_transform():
    spec = importlib.util.spec_from_file_location(
        "smoke_manifest", REPO / "hack" / "smoke-manifest.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _config_docs():
    """The kustomize output equivalent: every resource the tree lists.
    Guarded against kustomization features this reader can't emulate
    (patches, generators, directory resources): if config/ grows one,
    this fails LOUDLY so the reader gets upgraded (or swapped for real
    `kubectl kustomize` output) instead of silently pinning pre-patch
    documents that CI never applies."""
    kustomization = yaml.safe_load(
        (REPO / "config" / "kustomization.yaml").read_text()
    )
    unsupported = set(kustomization) - {
        "apiVersion", "kind", "resources"
    }
    assert not unsupported, (
        f"config/kustomization.yaml uses {sorted(unsupported)}; this "
        "test reads raw resource files and cannot emulate those — "
        "update it to run real `kubectl kustomize` output"
    )
    docs = []
    for rel in kustomization["resources"]:
        path = REPO / "config" / rel
        assert path.is_file(), (
            f"{rel}: directory/remote resources are not emulated here"
        )
        docs.extend(
            d for d in yaml.safe_load_all(path.read_text()) if d is not None
        )
    return docs


class TestSmokeTransform:
    def test_strips_exactly_the_kind_incompatible_docs(self):
        sm = _load_transform()
        # the script's OWN pipeline, not a re-implementation: a new
        # transform step is automatically under test
        kept = sm.transform(_config_docs(), "karpenter-tpu:smoke")
        kinds = {d.get("kind") for d in kept}
        # everything a bare kind cluster can't satisfy is gone
        assert not any(k.endswith("WebhookConfiguration") for k in kinds)
        assert all(
            not d.get("apiVersion", "").startswith(
                ("cert-manager.io/", "monitoring.coreos.com/")
            )
            for d in kept
        )
        # and the deployable core is intact
        assert {
            "CustomResourceDefinition",
            "ClusterRole",
            "ClusterRoleBinding",
            "ServiceAccount",
            "Deployment",
            "Namespace",
        } <= kinds

    def test_deployment_rewrite_invariants(self):
        sm = _load_transform()
        dep = next(
            d for d in _config_docs() if d.get("kind") == "Deployment"
        )
        sm.rewrite_deployment(dep, "karpenter-tpu:smoke")
        pod = dep["spec"]["template"]["spec"]
        assert dep["spec"]["replicas"] == 1
        assert "nodeSelector" not in pod
        # cert-manager volume dropped BY NAME; everything else kept
        names = [v["name"] for v in pod.get("volumes", [])]
        assert "cert" not in names
        for container in pod["containers"]:
            assert container["image"] == "karpenter-tpu:smoke"
            mounts = [
                m["name"] for m in container.get("volumeMounts", [])
            ]
            assert "cert" not in mounts
            for section in ("requests", "limits"):
                entries = container.get("resources", {}).get(section, {})
                assert "google.com/tpu" not in entries
        controller = next(
            c for c in pod["containers"] if c["name"] == "controller"
        )
        assert "--cloud-provider=fake" in controller["args"]
        assert not any("webhook" in a for a in controller["args"])
        # the solver keeps its compile cache (emptyDir works on kind)
        solver = next(
            c for c in pod["containers"] if c["name"] == "solver"
        )
        assert any(
            m["name"] == "compile-cache"
            for m in solver.get("volumeMounts", [])
        )


class TestChartTemplates:
    """No helm binary ships in this environment, so the chart renders
    nowhere before CI users run it; pin the cheap invariants a broken
    edit would trip (unbalanced actions, values references that do not
    exist in values.yaml)."""

    def test_actions_balanced_and_values_exist(self):
        import re

        chart = REPO / "charts" / "karpenter-tpu"
        values = yaml.safe_load((chart / "values.yaml").read_text())

        def has_path(root, dotted):
            node = root
            for part in dotted.split("."):
                if not isinstance(node, dict) or part not in node:
                    return False
                node = node[part]
            return True

        for template in sorted((chart / "templates").glob("*.yaml")):
            text = template.read_text()
            opens = len(re.findall(r"{{-?\s*(?:if|range|with)\b", text))
            ends = len(re.findall(r"{{-?\s*end\s*-?}}", text))
            assert opens == ends, (
                f"{template.name}: {opens} if/range/with vs {ends} end"
            )
            for dotted in re.findall(r"\.Values\.([A-Za-z0-9_.]+)", text):
                assert has_path(values, dotted), (
                    f"{template.name} references .Values.{dotted}, "
                    "absent from values.yaml"
                )
