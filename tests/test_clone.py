"""utils/clone.fast_clone: the store's copy primitive must be
indistinguishable from copy.deepcopy for API object trees (modulo the
documented Quantity sharing), and the store's copy-on-write discipline
must keep watcher-delivered objects frozen forever."""

import copy

from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
    Toleration,
)
from karpenter_tpu.api.horizontalautoscaler import (
    Behavior,
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
    ScalingPolicy,
    ScalingRules,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.api.serialization import to_dict
from karpenter_tpu.store import Store
from karpenter_tpu.utils.clone import fast_clone
from karpenter_tpu.utils.quantity import Quantity


def rich_pod():
    return Pod(
        metadata=ObjectMeta(
            name="p", namespace="ns", labels={"a": "b"},
            annotations={"k": "v"},
        ),
        spec=PodSpec(
            node_selector={"zone": "z1"},
            tolerations=[
                Toleration(
                    key="t", operator="Equal", value="v",
                    effect="NoSchedule",
                )
            ],
            containers=[
                Container(
                    requests={
                        "cpu": Quantity.parse("250m"),
                        "memory": Quantity.parse("1Gi"),
                    }
                )
            ],
        ),
    )


def rich_ha():
    return HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="sng"
            ),
            min_replicas=1,
            max_replicas=10,
            metrics=[
                Metric(
                    prometheus=PrometheusMetricSource(
                        query="q",
                        target=MetricTarget(type="Value", value=4.0),
                    )
                )
            ],
            behavior=Behavior(
                scale_up=ScalingRules(
                    stabilization_window_seconds=0,
                    policies=[
                        ScalingPolicy(
                            type="Count", value=4, period_seconds=60
                        )
                    ],
                )
            ),
        ),
    )


class TestFastClone:
    def test_equivalent_to_deepcopy_for_api_trees(self):
        for obj in (
            rich_pod(),
            rich_ha(),
            Node(
                metadata=ObjectMeta(name="n", labels={"g": "a"}),
                spec=NodeSpec(
                    taints=[Taint(key="k", value="v", effect="NoSchedule")]
                ),
                status=NodeStatus(
                    allocatable={"cpu": Quantity.parse("8")},
                    conditions=[NodeCondition(type="Ready", status="True")],
                ),
            ),
            ScalableNodeGroup(
                metadata=ObjectMeta(name="s"),
                spec=ScalableNodeGroupSpec(
                    replicas=3, type="AWSEC2AutoScalingGroup", id="arn:x"
                ),
            ),
        ):
            assert to_dict(fast_clone(obj)) == to_dict(copy.deepcopy(obj))

    def test_clone_is_independent(self):
        pod = rich_pod()
        clone = fast_clone(pod)
        clone.metadata.labels["a"] = "MUTATED"
        clone.spec.containers[0].requests["cpu"] = Quantity.parse("9")
        clone.spec.tolerations.append("x")
        assert pod.metadata.labels["a"] == "b"
        assert str(pod.spec.containers[0].requests["cpu"]) == "250m"
        assert len(pod.spec.tolerations) == 1

    def test_quantity_instances_shared(self):
        """Documented divergence from deepcopy: Quantity is immutable by
        contract and shared, which is what makes pod clones cheap."""
        pod = rich_pod()
        clone = fast_clone(pod)
        assert (
            clone.spec.containers[0].requests["cpu"]
            is pod.spec.containers[0].requests["cpu"]
        )

    def test_unknown_types_fall_back_to_deepcopy(self):
        class Odd:
            def __init__(self):
                self.payload = [1, 2]

        odd = Odd()
        clone = fast_clone(odd)
        assert clone is not odd and clone.payload == [1, 2]
        clone.payload.append(3)
        assert odd.payload == [1, 2]


class TestStoreCopyOnWrite:
    def test_watcher_view_frozen_across_status_patch(self):
        """_notify hands out the stored instance with no copy; the store
        must therefore never mutate it afterward — a status patch has to
        REPLACE the stored object (copy-on-write)."""
        store = Store()
        delivered = []
        store.watch("ScalableNodeGroup", lambda e, o: delivered.append(o))
        created = store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="s"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="AWSEC2AutoScalingGroup", id="arn:x"
                ),
            )
        )
        first = delivered[-1]
        rv_at_delivery = first.metadata.resource_version
        created.status.replicas = 7
        store.patch_status(created)
        # the originally-delivered instance did not change...
        assert first.metadata.resource_version == rv_at_delivery
        assert first.status.replicas != 7
        # ...the new event carries a DIFFERENT instance with the patch
        second = delivered[-1]
        assert second is not first
        assert second.status.replicas == 7

    def test_watcher_view_frozen_across_scale_update(self):
        from karpenter_tpu.store.store import Scale

        store = Store()
        delivered = []
        store.watch("ScalableNodeGroup", lambda e, o: delivered.append(o))
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="s", namespace="default"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="AWSEC2AutoScalingGroup", id="arn:x"
                ),
            )
        )
        first = delivered[-1]
        store.update_scale(
            "ScalableNodeGroup",
            Scale(
                namespace="default", name="s",
                spec_replicas=5, status_replicas=1,
            ),
        )
        assert first.spec.replicas == 1  # frozen
        assert delivered[-1].spec.replicas == 5
        assert store.get("ScalableNodeGroup", "default", "s").spec.replicas == 5


class TestDispatchEdges:
    def test_container_subclasses_not_flattened(self):
        """Exact-class dispatch: a dict subclass must keep its type (falls
        back to deepcopy), not silently become a plain dict."""

        class Labeled(dict):
            pass

        x = Labeled(a=[1, 2])
        clone = fast_clone(x)
        assert type(clone) is Labeled
        clone["a"].append(3)
        assert x["a"] == [1, 2]

    def test_frozen_dataclass_on_fast_path(self):
        """Frozen dataclasses clone via object.__setattr__ (no deepcopy
        demotion): Quantity leaves inside them stay shared."""
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class Frozen:
            xs: list
            q: Quantity

        q = Quantity.parse("2")
        f = Frozen(xs=[1], q=q)
        clone = fast_clone(f)
        assert clone.xs == [1] and clone.xs is not f.xs
        assert clone.q is q
