"""Native quantity parser: build, parity with the pure-Python oracle, and
fallback behavior.

The C parser (karpenter_tpu/native/quantity.c) must agree EXACTLY —
value as a Fraction and display format — with the regex+Fraction oracle in
utils/quantity.py for every string either accepts, and must reject (raise,
triggering fallback) anything outside its exact-arithmetic range rather
than silently losing precision.
"""

from fractions import Fraction

import pytest

from karpenter_tpu.native import load_kquantity
from karpenter_tpu.utils.quantity import (
    _NATIVE_FORMATS,
    _QUANTITY_RE,
    Quantity,
)

native = load_kquantity()

pytestmark = pytest.mark.skipif(
    native is None, reason="no C toolchain available"
)


def _regex_parse(s):
    """The pure-Python oracle, bypassing the native fast path."""
    from karpenter_tpu.utils.quantity import (
        _BINARY_SUFFIXES,
        _DECIMAL_SUFFIXES,
        BINARY_SI,
        DECIMAL_EXPONENT,
        DECIMAL_SI,
    )

    m = _QUANTITY_RE.match(s.strip())
    num = Fraction(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    suffix, exp = m.group("suffix"), m.group("exp")
    if suffix in _BINARY_SUFFIXES:
        return num * _BINARY_SUFFIXES[suffix], BINARY_SI
    if suffix is not None:
        return num * _DECIMAL_SUFFIXES[suffix], DECIMAL_SI
    if exp is not None:
        return num * Fraction(10) ** int(exp[1:]), DECIMAL_EXPONENT
    return num, DECIMAL_SI


CASES = [
    "0", "1", "100m", "1500m", "1100m", "25Gi", "99", "128500Mi", "1.5",
    "0.5", ".5", "5.", "1Ki", "2Mi", "3Ti", "4Pi", "1Ei", "1n", "2u",
    "3k", "4M", "5G", "6T", "7P", "1E", "-1", "-100m", "+2Gi", "1e3",
    "1E3", "2e-3", "1.25e2", "  25Gi  ", "0.000001", "123.456789",
    "110", "7600m", "48900m", "77Gi", "385500Mi", "150",
]


class TestParity:
    @pytest.mark.parametrize("s", CASES)
    def test_exact_value_and_format(self, s):
        num, den, fmt = native.parse(s)
        value, expect_format = _regex_parse(s)
        assert Fraction(num, den) == value, s
        assert _NATIVE_FORMATS[fmt] == expect_format, s

    def test_fuzz_against_oracle(self):
        import random

        rng = random.Random(11)
        suffixes = ["", "m", "k", "M", "G", "Ki", "Mi", "Gi", "Ti", "n",
                    "u", "T", "P", "E", "Pi", "Ei", "e2", "e-4", "E+6"]
        for _ in range(3000):
            mantissa = rng.choice(
                [
                    str(rng.randint(0, 10**rng.randint(1, 12))),
                    f"{rng.randint(0, 10**6)}.{rng.randint(0, 10**6)}",
                    f".{rng.randint(1, 10**6)}",
                ]
            )
            sign = rng.choice(["", "-", "+"])
            s = sign + mantissa + rng.choice(suffixes)
            try:
                num, den, fmt = native.parse(s)
            except ValueError:
                continue  # native declined; fallback handles it
            value, expect_format = _regex_parse(s)
            assert Fraction(num, den) == value, s
            assert _NATIVE_FORMATS[fmt] == expect_format, s

    @pytest.mark.parametrize(
        "s", ["", "abc", "1.2.3", "1X", "Ki", "--1", "1e", "1ee3", ".",
              "1 2", "0x10", "1\x00", "2.5\x00", "\x00", "1Gi\x00"]
    )
    def test_rejects_invalid(self, s):
        with pytest.raises(ValueError):
            native.parse(s)
        assert _QUANTITY_RE.match(s.strip()) is None

    def test_overflow_declines_instead_of_truncating(self):
        with pytest.raises(ValueError):
            native.parse("9" * 60)  # > u128
        # but the public API still parses it via the Python path
        assert Quantity.parse("9" * 60).value == Fraction("9" * 60)


class TestAsyncLoad:
    def test_background_build_becomes_visible(self):
        """The public parse path must converge to the native parser without
        ever blocking on the compile."""
        import time

        from karpenter_tpu import native as native_pkg
        from karpenter_tpu.utils.quantity import _native_parser

        _native_parser()  # kicks the async load (or it already ran)
        deadline = time.time() + 30
        while native_pkg.peek_kquantity() is None and time.time() < deadline:
            time.sleep(0.05)
        assert native_pkg.peek_kquantity() is not None
        assert _native_parser() is native_pkg.peek_kquantity()


class TestIntegration:
    def test_public_parse_uses_same_semantics(self):
        # whole pipeline: canonical formatting must be unchanged
        assert str(Quantity.parse("25Gi")) == "25Gi"
        assert str(Quantity.parse("1100m")) == "1100m"
        assert str(Quantity.parse("128500Mi")) == "128500Mi"
        assert Quantity.parse("1500m").to_float() == pytest.approx(1.5)
        total = Quantity()
        for _ in range(77):
            total = total.add(Quantity.parse("1Gi"))
        assert str(total) == "77Gi"

    def test_speedup_sanity(self):
        """The native path should beat the regex+Fraction oracle; parity
        matters more than the ratio, so just assert it is not slower."""
        import time

        strings = CASES * 200
        native_parse = native.parse
        t0 = time.perf_counter()
        for s in strings:
            try:
                native_parse(s)
            except ValueError:
                pass
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        for s in strings:
            _regex_parse(s)
        t_python = time.perf_counter() - t0
        assert t_native < t_python
