"""SimLab: the trace-driven fleet simulator (docs/simulator.md).

Pins the ISSUE 17 acceptance surface: kernel/device/mirror parity is
bitwise (the ops/simstep.py contract), replay is deterministic under
the seed, batched stepping equals the sequential loop, every registered
scenario survives a random fault schedule without blocking and recovers
its reactive fixed point, the searched policy beats the reactive
baseline on a seeded pinned episode, the live `simlab` algorithm honors
the never-block contract, the docs catalog table cannot drift from the
registry, and the published batched-vs-sequential speedup is guarded.
"""

import dataclasses
import json
import os
import time
from argparse import Namespace

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import simstep as SK
from karpenter_tpu.simlab import (
    BatchedSimEnv,
    SimEnv,
    SimParams,
    catalog,
    catalog_text,
    get_scenario,
    register_scenario,
    scenarios,
    select_for,
)
from karpenter_tpu.simlab.builtin import make_trails
from karpenter_tpu.simlab.policy import (
    FROZEN_KNOBS,
    REACTIVE_KNOBS,
    ReactivePolicy,
    SearchTunedPolicy,
    search_tuned_policy,
)
from karpenter_tpu.simlab.registry import Scenario
from karpenter_tpu.solver.service import SolverService

_F32 = np.float32

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _svc() -> SolverService:
    """A private service per test: own gauge registry, fresh stats."""
    return SolverService(registry=GaugeRegistry())


def _scalars(p: SimParams) -> dict:
    return {
        "cap": _F32(p.cap),
        "hourly": _F32(p.hourly),
        "step_limit": _F32(p.step_limit),
        "min_replicas": _F32(p.min_replicas),
        "max_replicas": _F32(p.max_replicas),
    }


def _batched_inputs(seeds, knobs, ticks=32, rows=4):
    """Batched SimRolloutInputs over independently-seeded episodes with
    every trail kind exercised (diurnal demand, price spikes, faults)."""
    trails = [
        make_trails(
            s, ticks=ticks, rows=rows, diurnal=True, amplitude=40.0,
            price_spike=1.5, fault_probability=0.2,
        )
        for s in seeds
    ]
    return SK.SimRolloutInputs(
        replicas0=np.stack([t.replicas0 for t in trails]),
        streak0=np.zeros((len(trails), rows), _F32),
        demand=np.stack([t.demand for t in trails]),
        forecast=np.stack([t.forecast for t in trails]),
        price=np.stack([t.price for t in trails]),
        fault=np.stack([t.fault for t in trails]),
        knobs=np.broadcast_to(
            np.asarray(knobs, _F32), (len(trails), SK.KNOBS)
        ).copy(),
        **_scalars(SimParams()),
    )


def _assert_rollout_equal(a, b):
    """Bitwise equality across every SimRolloutOutputs field."""
    for name in ("replicas", "streak", "violation", "cost", "backlog",
                 "target"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=f"rollout field {name} diverged",
        )


class TestKernelParity:
    """ops/simstep.py: jit == numpy mirror == vmapped, bitwise."""

    def test_step_jit_matches_numpy_bitwise(self):
        trails = make_trails(
            1, ticks=8, rows=6, spike=30.0, price_spike=2.0,
            fault_probability=0.5,
        )
        for t in range(4):
            inputs = SK.SimStepInputs(
                replicas=trails.replicas0,
                target=trails.demand[t] / _F32(2.0),
                demand=trails.demand[t],
                price=np.asarray(trails.price[t]),
                fault=np.asarray(trails.fault[t]),
                **_scalars(SimParams()),
            )
            dev = SK.sim_step_jit(inputs)
            host = SK.sim_step_numpy(inputs)
            for name in ("replicas", "violation", "cost", "backlog"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(dev, name)),
                    np.asarray(getattr(host, name)),
                    err_msg=f"tick {t} field {name} diverged",
                )

    def test_batched_step_matches_numpy_bitwise(self):
        trails = [make_trails(10 + i, ticks=4, rows=5) for i in range(3)]
        inputs = SK.SimStepInputs(
            replicas=np.stack([t.replicas0 for t in trails]),
            target=np.stack([t.demand[0] for t in trails]),
            demand=np.stack([t.demand[0] for t in trails]),
            price=np.stack([t.price[0] for t in trails]),
            fault=np.stack([t.fault[0] for t in trails]),
            **_scalars(SimParams()),
        )
        dev = SK.sim_step_jit(inputs)
        host = SK.sim_step_numpy(inputs)
        for name in ("replicas", "violation", "cost", "backlog"):
            np.testing.assert_array_equal(
                np.asarray(getattr(dev, name)),
                np.asarray(getattr(host, name)),
            )

    def test_vmapped_rollout_matches_per_cluster_jit_and_numpy(self):
        """The batched == sequential property pin, on DEVICE and on the
        mirror: one vmapped program over B clusters is bitwise the
        per-cluster scan loop and the numpy reference."""
        inputs = _batched_inputs(range(20, 24), FROZEN_KNOBS)
        batched = SK.sim_rollout_vmapped(inputs)
        host = SK.sim_rollout_numpy(inputs)
        _assert_rollout_equal(batched, host)
        for b in range(4):
            solo = SK.sim_rollout_jit(SK._cluster_slice(inputs, b))
            for name in ("replicas", "violation", "cost", "backlog",
                         "target"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(batched, name))[b],
                    np.asarray(getattr(solo, name)),
                    err_msg=f"cluster {b} field {name} diverged",
                )


class TestServiceSeam:
    """SolverService.sim_step/sim_rollout: one batched dispatch, the
    never-block mirror, and honest dispatch accounting."""

    def test_one_batched_dispatch_vs_b_sequential(self):
        svc = _svc()
        inputs = _batched_inputs(range(30, 34), FROZEN_KNOBS)
        batched = svc.sim_rollout(inputs, backend="xla")
        assert svc.stats.sim_calls == 1
        assert svc.stats.sim_dispatches == 1
        assert svc.stats.sim_mirror_serves == 0
        for b in range(4):
            solo = svc.sim_rollout(
                SK._cluster_slice(inputs, b), backend="xla"
            )
            np.testing.assert_array_equal(
                np.asarray(batched.replicas)[b], np.asarray(solo.replicas)
            )
        # the sequential loop paid B more dispatches for the same bits
        assert svc.stats.sim_calls == 5
        assert svc.stats.sim_dispatches == 5

    def test_device_fault_serves_bit_identical_mirror(self):
        """NEVER-BLOCK: a device failure at the `simlab.step` injection
        point serves the numpy mirror — same bits, no exception."""
        from karpenter_tpu.faults.registry import injected_faults

        inputs = _batched_inputs(range(40, 43), REACTIVE_KNOBS)
        svc = _svc()
        with injected_faults(seed=5) as reg:
            reg.plan("simlab.step", mode="error", probability=1.0)
            out = svc.sim_rollout(inputs, backend="xla")
        assert svc.stats.sim_mirror_serves == 1
        assert svc.stats.sim_dispatches == 0
        _assert_rollout_equal(out, SK.sim_rollout_numpy(inputs))


class TestSimEnv:
    def test_replay_twice_is_identical(self):
        env = SimEnv(get_scenario("forecast").trails, seed=3)
        first = env.run()
        second = env.run()
        assert first["reward"] == second["reward"]
        assert first["violation_ticks"] == second["violation_ticks"]
        assert first["hourly_cost"] == second["hourly_cost"]
        np.testing.assert_array_equal(
            first["final_replicas"], second["final_replicas"]
        )

    def test_distinct_seeds_draw_distinct_episodes(self):
        env = SimEnv(get_scenario("forecast").trails, seed=3)
        a = env.run()
        env.reset(seed=4)
        b = env.run(reset=False)
        assert a["reward"] != b["reward"]

    def test_unusable_actions_fall_back_to_reactive(self):
        env = SimEnv(get_scenario("cost").trails, seed=1)
        _obs, _r, _d, info = env.step(np.zeros(3, _F32))  # wrong shape
        assert info["reactive_fallback"]
        nan = np.full(env.trails.rows, np.nan, _F32)
        _obs, _r, _d, info = env.step(nan)
        assert info["reactive_fallback"]
        _obs, _r, _d, info = env.step(None)  # reactive BY CHOICE
        assert not info["reactive_fallback"]

    def test_step_after_done_raises(self):
        env = SimEnv(
            lambda seed: make_trails(seed, ticks=2, rows=2), seed=0
        )
        env.run()
        with pytest.raises(RuntimeError, match="done"):
            env.step(None)


class TestBatchedMatchesSequential:
    def test_rollout_equals_sequential_gym_loops(self):
        """The batched vmapped rollout and B sequential gym loops under
        the host SearchTunedPolicy tell the same story: same final
        replicas bitwise, same composite rewards (the host loop sums
        per-tick in a different order, hence approx not bitwise)."""
        trails_fn = get_scenario("forecast").trails
        batched = BatchedSimEnv(trails_fn, clusters=3, seed=3)
        out = batched.rollout(FROZEN_KNOBS)
        for i in range(3):
            env = SimEnv(trails_fn, seed=3 + i)
            run = env.run(SearchTunedPolicy(FROZEN_KNOBS))
            np.testing.assert_array_equal(
                out["final_replicas"][i], run["final_replicas"]
            )
            assert run["reward"] == pytest.approx(
                float(out["rewards"][i]), rel=1e-9
            )
            assert run["policy_faults"] == 0

    def test_reactive_knobs_are_the_reactive_baseline(self):
        """knobs (0,0,0) IS the reactive policy — the property that lets
        every comparison share one compiled program."""
        trails_fn = get_scenario("cost").trails
        kernel = SimEnv(trails_fn, seed=2).run(
            SearchTunedPolicy(REACTIVE_KNOBS)
        )
        reactive = SimEnv(trails_fn, seed=2).run(ReactivePolicy())
        assert kernel["reward"] == reactive["reward"]
        np.testing.assert_array_equal(
            kernel["final_replicas"], reactive["final_replicas"]
        )


class _FlakyPolicy:
    """Raises some ticks, emits poison some ticks — the fuzz adversary;
    the env must degrade those ticks to reactive and keep stepping."""

    def __init__(self):
        self._t = 0

    def reset(self):
        self._t = 0

    def act(self, obs):
        self._t += 1
        if self._t % 5 == 1:
            raise RuntimeError("injected policy fault")
        if self._t % 5 == 3:
            return np.full_like(obs["replicas"], np.nan)
        return None  # reactive by choice


class TestNeverBlockFuzz:
    def test_every_scenario_survives_random_faults_and_recovers(self):
        """Satellite (c): every registered scenario, stepped end to end
        under a RANDOM fault schedule and a misbehaving policy — no
        exception escapes, and once faults clear (the trail generators'
        fault-free constant tail) the fleet recovers the reactive fixed
        point ceil(tail demand / cap)."""
        for fuzz_seed, (name, sc) in enumerate(scenarios().items()):
            assert sc.trails is not None, f"{name} has no trails"

            def fuzzed(seed, sc=sc, fuzz_seed=fuzz_seed):
                trails = sc.trails(seed)
                rng = np.random.default_rng(1000 + fuzz_seed)
                tail = max(1, trails.ticks // 4)
                fault = (rng.random(trails.ticks) < 0.3).astype(_F32)
                fault[trails.ticks - tail:] = 0.0
                return dataclasses.replace(trails, fault=fault)

            env = SimEnv(fuzzed, params=sc.params, seed=11)
            run = env.run(_FlakyPolicy())
            assert run["policy_faults"] > 0, name
            assert run["reactive_fallbacks"] > 0, name
            p = sc.params
            expected = np.clip(
                np.ceil(env.trails.demand[-1] / _F32(p.cap)),
                _F32(p.min_replicas), _F32(p.max_replicas),
            ).astype(_F32)
            np.testing.assert_array_equal(
                run["final_replicas"], expected,
                err_msg=f"{name} did not recover its reactive fixed "
                f"point after the fault tail cleared",
            )


class TestPolicySearch:
    def test_search_beats_reactive_pinned(self):
        """Acceptance: SearchTunedPolicy beats the reactive baseline on
        the forecast scenario's composite reward — seeded, with the
        winning knob vector pinned."""
        result = search_tuned_policy(
            get_scenario("forecast").trails, seed=3
        )
        assert tuple(float(k) for k in result.knobs) == (1.0, 0.0, 4.0)
        assert result.margin > 0
        assert result.reward > result.baseline_reward
        assert result.dispatches == 2  # grid round + refinement round
        assert result.candidates == len(result.rewards)
        assert tuple(float(k) for k in REACTIVE_KNOBS) in result.rewards

    def test_baseline_reward_is_the_reactive_gym_reward(self):
        trails_fn = get_scenario("forecast").trails
        result = search_tuned_policy(trails_fn, seed=3, refine=False)
        reactive = SimEnv(trails_fn, seed=3).run(ReactivePolicy())
        assert result.baseline_reward == pytest.approx(
            reactive["reward"], rel=1e-9
        )

    def test_winner_replays_its_searched_score_in_the_gym_loop(self):
        """The host SearchTunedPolicy runs the SAME f32 math the search
        scored in-kernel, so the frozen winner keeps its score."""
        trails_fn = get_scenario("forecast").trails
        result = search_tuned_policy(trails_fn, seed=3)
        run = SimEnv(trails_fn, seed=3).run(result.policy())
        assert run["reward"] == pytest.approx(result.reward, rel=1e-9)

    def test_broken_policy_degrades_to_the_reactive_episode(self):
        class Boom:
            def reset(self):
                pass

            def act(self, obs):
                raise RuntimeError("always broken")

        trails_fn = get_scenario("cost").trails
        env = SimEnv(trails_fn, seed=1)
        broken = env.run(Boom())
        reactive = env.run(None)
        assert broken["policy_faults"] == env.trails.ticks
        assert broken["reward"] == reactive["reward"]


class TestScenarioRegistry:
    EXPECTED = (
        "trace", "constraints", "eventloop", "multitenant",
        "poolgroups", "cost", "forecast", "restart-storm", "failover",
        "preempt", "consolidate", "what-if", "karpenter",
    )

    @staticmethod
    def _args(**over):
        base = dict(
            trace_export=None, constraints=False, eventloop=False,
            multitenant=False, poolgroups=False, cost=False,
            forecast=False, restart_storm=False, failover=False,
            preempt=False, consolidate=False, what_if=None,
            sim_seed=None,
        )
        base.update(over)
        return Namespace(**base)

    def test_catalog_names_and_order(self):
        assert tuple(scenarios()) == self.EXPECTED

    def test_selection_precedence_matches_the_old_elif_chain(self):
        assert select_for(self._args()).name == "karpenter"
        assert select_for(self._args(constraints=True)).name == "constraints"
        # lower order wins when several flags are set
        assert select_for(
            self._args(constraints=True, cost=True)
        ).name == "constraints"
        # --trace-export combines with other worlds instead of winning
        assert select_for(
            self._args(trace_export="t.jsonl", cost=True)
        ).name == "cost"
        assert select_for(
            self._args(trace_export="t.jsonl")
        ).name == "trace"

    def test_duplicate_registration_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            register_scenario(Scenario(
                name="cost", description="dup", flags="--cost",
                order=999, select=lambda a: False,
                run=lambda a, s: 0,
            ))

    def test_unknown_scenario_lists_the_known(self):
        with pytest.raises(KeyError, match="registered:"):
            get_scenario("nope")

    def test_catalog_text_mentions_every_scenario(self):
        text = catalog_text()
        for name in self.EXPECTED:
            assert name in text
        assert "--sim-seed" in text


class TestDocDrift:
    """docs/simulator.md catalog table <-> registry, two directions —
    the metrics-lint discipline (tests/test_metrics.py) applied to the
    scenario catalog."""

    @staticmethod
    def _doc_rows():
        import re

        text = open(os.path.join(REPO_ROOT, "docs", "simulator.md")).read()
        section = text.split("## Scenario registry", 1)
        assert len(section) == 2, (
            "docs/simulator.md must carry the 'Scenario registry' section"
        )
        body = section[1].split("\n## ", 1)[0]
        rows = {}
        for match in re.finditer(
            r"^\| `([a-z-]+)` \| ([^|]+) \| ([^|]+) \| ([^|]+) \|",
            body, re.MULTILINE,
        ):
            rows[match.group(1)] = (
                match.group(2).strip().strip("`"),
                match.group(3).strip().strip("`"),
            )
        assert rows, "the scenario catalog table parsed empty"
        return rows

    def test_every_registered_scenario_is_documented(self):
        documented = set(self._doc_rows())
        missing = set(scenarios()) - documented
        assert not missing, (
            f"registered but missing from the docs/simulator.md catalog "
            f"table: {sorted(missing)}"
        )

    def test_every_documented_scenario_is_registered(self):
        stale = set(self._doc_rows()) - set(scenarios())
        assert not stale, (
            f"documented in docs/simulator.md but not registered: "
            f"{sorted(stale)}"
        )

    def test_flags_and_seededness_agree(self):
        rows = self._doc_rows()
        for name, _desc, flags, seeded in catalog():
            doc_flags, doc_seeded = rows[name]
            assert doc_flags == flags, (
                f"{name}: docs say flags {doc_flags!r}, registry says "
                f"{flags!r}"
            )
            expected = "--sim-seed" if seeded else "fixed"
            assert doc_seeded == expected, (
                f"{name}: docs say {doc_seeded!r}, registry says "
                f"{expected!r}"
            )


class TestCLI:
    def test_simulate_list_prints_the_catalog(self, tmp_path, capsys):
        from karpenter_tpu.__main__ import main

        rc = main([
            "--simulate", "--list",
            "--data-dir", str(tmp_path / "s"), "--no-leader-elect",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        for name in TestScenarioRegistry.EXPECTED:
            assert name in out

    def test_default_seed_digests_pinned_and_sim_seed_threads(
        self, tmp_path, capsys
    ):
        """Satellite (a): the default seed reproduces the pre-registry
        CLI byte-identically (the pinned constraints digests), and
        --sim-seed actually reaches the world's RNG streams."""
        from karpenter_tpu.__main__ import main

        common = ["--data-dir", str(tmp_path / "s"), "--no-leader-elect"]
        rc = main(["--simulate", "--constraints"] + common)
        assert rc == 0
        default = json.loads(capsys.readouterr().out)
        assert default["digests"] == {
            "before": 1761739094,
            "after": 2968639679,
        }
        assert default["dead_zone"] == "z3"

        rc = main(
            ["--simulate", "--constraints", "--sim-seed", "8"] + common
        )
        assert rc == 0
        reseeded = json.loads(capsys.readouterr().out)
        assert reseeded["dead_zone"] == "z2"  # seed 8 kills a new zone
        assert reseeded["digests"] != default["digests"]


class TestSimlabAlgorithm:
    """The live hook: the frozen tuned policy as a registered algorithm
    behind the never-block contract."""

    @staticmethod
    def _metric(value, at, target=4.0):
        from karpenter_tpu.api.horizontalautoscaler import AVERAGE_VALUE
        from karpenter_tpu.autoscaler.algorithms import Metric

        return Metric(
            value=value, target_type=AVERAGE_VALUE, target_value=target,
            name="qps", owner=("default", "ha"), at=at,
        )

    def test_registered(self):
        from karpenter_tpu.autoscaler.algorithms import known_algorithms

        assert "simlab" in known_algorithms()

    def test_first_tick_is_plain_proportional(self):
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy()
        assert algo.get_desired_replicas(self._metric(16.0, at=1.0), 4) == 4

    def test_ramp_scales_to_the_projection(self):
        """blend floor 1.0: a 16 -> 24 ramp projects to 32, so the
        desired count provisions the ramp ahead of the data."""
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy()
        assert algo.get_desired_replicas(self._metric(16.0, at=1.0), 4) == 4
        assert algo.get_desired_replicas(self._metric(24.0, at=2.0), 4) == 8

    def test_scale_down_held_for_the_stabilization_window(self):
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy()  # FROZEN_KNOBS: stab_window 2
        assert algo.get_desired_replicas(self._metric(32.0, at=1.0), 8) == 8
        # demand collapses: held for two ticks, released on the third
        assert algo.get_desired_replicas(self._metric(4.0, at=2.0), 8) == 8
        assert algo.get_desired_replicas(self._metric(4.0, at=3.0), 8) == 8
        assert algo.get_desired_replicas(self._metric(4.0, at=4.0), 8) == 1

    def test_scale_up_is_never_held(self):
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy(knobs=[0.0, 0.0, 8.0])  # window only
        assert algo.get_desired_replicas(self._metric(4.0, at=1.0), 1) == 1
        assert algo.get_desired_replicas(self._metric(32.0, at=2.0), 1) == 8

    def test_poisoned_metric_never_blocks(self):
        """NaN reaches both the tuned path and the reactive fallback —
        the algorithm holds the fleet instead of raising."""
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy()
        assert algo.get_desired_replicas(
            self._metric(float("nan"), at=1.0), 6
        ) == 6

    def test_clock_backwards_does_not_project(self):
        from karpenter_tpu.autoscaler.algorithms.simlab_policy import (
            SimlabPolicy,
        )

        algo = SimlabPolicy()
        assert algo.get_desired_replicas(self._metric(16.0, at=9.0), 4) == 4
        # an older sample must not become a projection base
        assert algo.get_desired_replicas(self._metric(24.0, at=5.0), 4) == 6


class TestLabels:
    class _FakeLedger:
        def __init__(self, records):
            self._records = records

        def query(self, kind=None, tenant=None, limit=None):
            return list(self._records)

    def test_stage_index_is_stable(self):
        from karpenter_tpu.observability.provenance import STAGES
        from karpenter_tpu.simlab import stage_index

        for i, stage in enumerate(STAGES):
            assert stage_index(stage) == i
        assert stage_index("unknown") == -1
        assert stage_index(None) == -1

    def test_label_stream_reshapes_and_nan_pads(self):
        from karpenter_tpu.observability.provenance import STAGES
        from karpenter_tpu.simlab import label_stream
        from karpenter_tpu.simlab.labels import FEATURE_NAMES

        ledger = self._FakeLedger([{
            "prev_replicas": 3, "base_desired": 5,
            "forecast_value": None, "forecast_skill": 0.9,
            "cost_hourly": 1.5, "cost_risk": None,
            "observed": [7.0],
            "final_desired": 4, "winning_stage": STAGES[0],
            "kind": "ha", "tenant": "blue", "name": "web",
            "group": "tpu",
        }])
        rows = label_stream(ledger)
        assert len(rows) == 1
        row = rows[0]
        assert len(row["features"]) == len(FEATURE_NAMES)
        assert row["features"][0] == 3.0
        assert np.isnan(row["features"][2])  # None forecast -> NaN
        assert row["features"][6] == 7.0  # observed_0
        assert np.isnan(row["features"][7])  # observed_1 padded
        assert row["label_desired"] == 4.0
        assert row["label_stage"] == 0
        assert row["tenant"] == "blue"


class TestRegressionGuard:
    def test_published_speedup_is_at_least_5x(self):
        """Acceptance: make bench-simlab published >= 5x batched vs
        sequential to BASELINE.json, parity pinned bitwise first."""
        baseline = json.load(
            open(os.path.join(REPO_ROOT, "BASELINE.json"))
        )
        records = {
            key: rec
            for key, rec in baseline.get("published", {}).items()
            if " simlab (" in key
        }
        assert records, (
            "no simlab record in BASELINE.json — run `make bench-simlab`"
        )
        for key, rec in records.items():
            assert rec["speedup"] >= 5.0, (key, rec["speedup"])
            assert rec["parity"] == "bitwise", key

    def test_batched_beats_sequential_live(self):
        """Non-slow live guard for the bench-simlab claim: ONE vmapped
        dispatch must beat the per-cluster loop (generously — the
        published numbers live in docs/BENCHMARKS.md / BASELINE.json)."""
        svc = _svc()
        inputs = _batched_inputs(range(16), FROZEN_KNOBS, ticks=64,
                                 rows=8)
        solos = [SK._cluster_slice(inputs, b) for b in range(16)]
        svc.sim_rollout(inputs, backend="xla")  # warm the vmapped jit
        svc.sim_rollout(solos[0], backend="xla")  # warm the solo jit
        assert svc.stats.sim_mirror_serves == 0

        best_batched = min(
            self._timed(lambda: svc.sim_rollout(inputs, backend="xla"))
            for _ in range(3)
        )
        best_sequential = min(
            self._timed(lambda: [
                svc.sim_rollout(s, backend="xla") for s in solos
            ])
            for _ in range(3)
        )
        assert best_batched * 2 < best_sequential, (
            f"batched {best_batched * 1e3:.3f}ms vs sequential "
            f"{best_sequential * 1e3:.3f}ms"
        )

    @staticmethod
    def _timed(fn):
        start = time.perf_counter()
        fn()
        return time.perf_counter() - start
