"""End-to-end convergence tests — the envtest-suite analog.

Scenarios and golden values from the reference suites:
- HA: pkg/controllers/horizontalautoscaler/v1alpha1/suite_test.go:94-118
  (metric=.85 target=60% replicas=5 → 8; queue=41 target=4 → 11)
- MP: pkg/controllers/metricsproducer/v1alpha1/suite_test.go:64-123
  (reserved-capacity status strings incl. the NaN empty-group case)
- SNG: pkg/controllers/scalablenodegroup/v1alpha1/suite_test.go:82-124
  (scale up/down/no-op, stabilized propagation, retryable errors)

Unlike the reference (which mocks Prometheus with ghttp), the queue scenario
here exercises the REAL in-process pipeline: producer → gauge registry →
registry metrics client → batched decision kernel → scale subresource →
provider actuation.
"""

from dataclasses import dataclass

import pytest

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    resource_list,
)
from karpenter_tpu.api.horizontalautoscaler import (
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    QueueSpec,
    ReservedCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory, retryable_error
from karpenter_tpu.runtime import KarpenterRuntime


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


@pytest.fixture
def env():
    clock = FakeClock()
    provider = FakeFactory()
    runtime = KarpenterRuntime(cloud_provider_factory=provider, clock=clock)
    return runtime, provider, clock


def utilization_ha(name="microservices", queries=("karpenter_reserved_capacity_cpu_utilization",
                                                  "karpenter_reserved_capacity_memory_utilization")):
    """docs/examples/reserved-capacity-utilization.yaml shape."""
    return HorizontalAutoscaler(
        metadata=ObjectMeta(name=name),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name=name
            ),
            min_replicas=3,
            max_replicas=23,
            metrics=[
                Metric(
                    prometheus=PrometheusMetricSource(
                        query=f'{q}{{name="{name}"}}',
                        target=MetricTarget(type="Utilization", value=60),
                    )
                )
                for q in queries
            ],
        ),
    )


def sng_of(name, replicas=1, group_id=None):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id=group_id or name
        ),
    )


def all_happy(store, obj):
    fresh = store.get(obj.KIND, obj.metadata.namespace, obj.metadata.name)
    return fresh.status_conditions().is_happy(), fresh


class TestHorizontalAutoscalerE2E:
    def test_utilization_85_target_60_replicas_5_wants_8(self, env):
        runtime, provider, clock = env
        name = "microservices"
        # mock the metric the way the reference's ghttp server does
        for resource in ("cpu", "memory"):
            gauge = runtime.registry.register(
                "reserved_capacity", f"{resource}_utilization"
            )
            gauge.set(name, "default", 0.85)
        provider.node_replicas[name] = 5
        runtime.store.create(sng_of(name, replicas=5))
        runtime.store.create(utilization_ha(name))

        runtime.manager.reconcile_all()  # SNG observes 5, HA decides
        runtime.manager.reconcile_all()  # SNG actuates the scale write

        happy, ha = all_happy(runtime.store, utilization_ha(name))
        assert ha.status.desired_replicas == 8
        assert happy, [
            (c.type, c.status, c.message) for c in ha.status.conditions
        ]
        assert provider.node_replicas[name] == 8

        # status.replicas reflects the observation at reconcile start (same
        # as the reference); the next interval's loop observes the new count
        clock.advance(61)
        runtime.manager.reconcile_all()
        happy_sng, sng = all_happy(runtime.store, sng_of(name))
        assert sng.status.replicas == 8
        assert happy_sng

    def test_queue_41_target_4_full_pipeline_wants_11(self, env):
        """Full in-process pipeline: queue producer -> gauge -> registry
        client -> batched kernel -> scale subresource -> fake provider."""
        runtime, provider, clock = env
        queue_id = "arn:aws:sqs:us-west-2:1234567890:ml-training-queue"
        provider.queue_lengths[queue_id] = 41
        provider.node_replicas["ml-training-capacity"] = 1

        runtime.store.create(
            MetricsProducer(
                metadata=ObjectMeta(name="ml-training-queue"),
                spec=MetricsProducerSpec(
                    queue=QueueSpec(type="FakeQueue", id=queue_id)
                ),
            )
        )
        runtime.store.create(sng_of("ml-training-capacity"))
        runtime.store.create(
            HorizontalAutoscaler(
                metadata=ObjectMeta(name="ml-training-capacity-autoscaler"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name="ml-training-capacity"
                    ),
                    min_replicas=0,
                    max_replicas=1000,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query='karpenter_queue_length{name="ml-training-queue"}',
                                target=MetricTarget(type="AverageValue", value=4),
                            )
                        )
                    ],
                ),
            )
        )

        runtime.manager.reconcile_all()
        runtime.manager.reconcile_all()

        ha = runtime.store.get(
            "HorizontalAutoscaler", "default", "ml-training-capacity-autoscaler"
        )
        assert ha.status.desired_replicas == 11
        assert ha.status_conditions().is_happy()
        assert provider.node_replicas["ml-training-capacity"] == 11
        mp = runtime.store.get("MetricsProducer", "default", "ml-training-queue")
        assert mp.status.queue.length == 41

    def test_stabilization_window_holds_scale_down_then_releases(self, env):
        runtime, provider, clock = env
        name = "svc"
        gauge = runtime.registry.register("queue", "length")
        gauge.set("q", "default", 100.0)
        provider.node_replicas[name] = 1
        runtime.store.create(sng_of(name))
        runtime.store.create(
            HorizontalAutoscaler(
                metadata=ObjectMeta(name=name),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name=name
                    ),
                    min_replicas=0,
                    max_replicas=100,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query='karpenter_queue_length{name="q"}',
                                target=MetricTarget(type="AverageValue", value=4),
                            )
                        )
                    ],
                ),
            )
        )
        runtime.manager.reconcile_all()
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        assert ha.status.desired_replicas == 25  # 100/4

        # queue drains; within the 300s default window scale-down is held
        gauge.set("q", "default", 4.0)
        clock.advance(30)
        runtime.manager.reconcile_all()
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        scale = runtime.store.get_scale("ScalableNodeGroup", "default", name)
        assert scale.spec_replicas == 25  # held
        able = ha.status_conditions().get(cond.ABLE_TO_SCALE)
        assert able.status == cond.FALSE
        assert "within stabilization window" in able.message

        # after the window expires the scale-down proceeds
        clock.advance(301)
        runtime.manager.reconcile_all()
        scale = runtime.store.get_scale("ScalableNodeGroup", "default", name)
        assert scale.spec_replicas == 1
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        assert ha.status_conditions().get(cond.ABLE_TO_SCALE).status == cond.TRUE

    def test_scaling_policy_rate_limits_scale_up(self, env):
        """Count policy with periodSeconds applied end-to-end — the
        reference models these (horizontalautoscaler.go:111-146) but never
        applies them (autoscaler.go:186-189 TODO)."""
        from karpenter_tpu.api.horizontalautoscaler import (
            Behavior,
            ScalingPolicy,
            ScalingRules,
        )

        runtime, provider, clock = env
        name = "burst"
        gauge = runtime.registry.register("queue", "length")
        gauge.set("q", "default", 400.0)
        provider.node_replicas[name] = 1
        runtime.store.create(sng_of(name))
        runtime.store.create(
            HorizontalAutoscaler(
                metadata=ObjectMeta(name=name),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name=name
                    ),
                    min_replicas=0,
                    max_replicas=1000,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query='karpenter_queue_length{name="q"}',
                                target=MetricTarget(
                                    type="AverageValue", value=4
                                ),
                            )
                        )
                    ],
                    behavior=Behavior(
                        scale_up=ScalingRules(
                            policies=[
                                ScalingPolicy(
                                    type="Count", value=4, period_seconds=60
                                )
                            ]
                        )
                    ),
                ),
            )
        )
        # first scale: no LastScaleTime -> no history to rate-limit against
        runtime.manager.reconcile_all()
        scale = runtime.store.get_scale("ScalableNodeGroup", "default", name)
        assert scale.spec_replicas == 100  # 400/4

        # demand doubles 10s later: inside the 60s period the budget is
        # conservatively spent -> full hold, AbleToScale false
        gauge.set("q", "default", 800.0)
        clock.advance(10)
        runtime.manager.reconcile_all()
        scale = runtime.store.get_scale("ScalableNodeGroup", "default", name)
        assert scale.spec_replicas == 100
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        able = ha.status_conditions().get(cond.ABLE_TO_SCALE)
        assert able.status == cond.FALSE
        assert "scaling policy budget spent" in able.message

        # period elapses: 4 replicas allowed, not the full jump to 200
        clock.advance(61)
        runtime.manager.reconcile_all()
        scale = runtime.store.get_scale("ScalableNodeGroup", "default", name)
        assert scale.spec_replicas == 104
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        assert ha.status_conditions().get(cond.ABLE_TO_SCALE).status == cond.TRUE

    def test_bounds_clamp_marks_scaling_bounded(self, env):
        runtime, provider, clock = env
        name = "svc"
        runtime.registry.register("queue", "length").set("q", "default", 1000.0)
        provider.node_replicas[name] = 1
        runtime.store.create(sng_of(name))
        ha_obj = HorizontalAutoscaler(
            metadata=ObjectMeta(name=name),
            spec=HorizontalAutoscalerSpec(
                scale_target_ref=CrossVersionObjectReference(
                    kind="ScalableNodeGroup", name=name
                ),
                min_replicas=0,
                max_replicas=10,
                metrics=[
                    Metric(
                        prometheus=PrometheusMetricSource(
                            query='karpenter_queue_length{name="q"}',
                            target=MetricTarget(type="AverageValue", value=4),
                        )
                    )
                ],
            ),
        )
        runtime.store.create(ha_obj)
        runtime.manager.reconcile_all()
        ha = runtime.store.get("HorizontalAutoscaler", "default", name)
        assert ha.status.desired_replicas == 10
        unbounded = ha.status_conditions().get(cond.SCALING_UNBOUNDED)
        assert unbounded.status == cond.FALSE
        assert "limited by bounds [0, 10]" in unbounded.message

    def test_metric_error_marks_not_active_without_failing_others(self, env):
        runtime, provider, clock = env
        provider.node_replicas["good"] = 1
        runtime.registry.register("queue", "length").set("q", "default", 8.0)
        runtime.store.create(sng_of("good"))
        good = HorizontalAutoscaler(
            metadata=ObjectMeta(name="good"),
            spec=HorizontalAutoscalerSpec(
                scale_target_ref=CrossVersionObjectReference(
                    kind="ScalableNodeGroup", name="good"
                ),
                min_replicas=0,
                max_replicas=100,
                metrics=[
                    Metric(
                        prometheus=PrometheusMetricSource(
                            query='karpenter_queue_length{name="q"}',
                            target=MetricTarget(type="AverageValue", value=4),
                        )
                    )
                ],
            ),
        )
        bad = HorizontalAutoscaler(
            metadata=ObjectMeta(name="bad"),
            spec=HorizontalAutoscalerSpec(
                scale_target_ref=CrossVersionObjectReference(
                    kind="ScalableNodeGroup", name="missing-target"
                ),
                min_replicas=0,
                max_replicas=100,
                metrics=[
                    Metric(
                        prometheus=PrometheusMetricSource(
                            query='karpenter_no_such_metric{name="q"}',
                            target=MetricTarget(type="AverageValue", value=4),
                        )
                    )
                ],
            ),
        )
        runtime.store.create(good)
        runtime.store.create(bad)
        runtime.manager.reconcile_all()

        good_fresh = runtime.store.get("HorizontalAutoscaler", "default", "good")
        bad_fresh = runtime.store.get("HorizontalAutoscaler", "default", "bad")
        assert good_fresh.status.desired_replicas == 2
        assert (
            good_fresh.status_conditions().get(cond.ACTIVE).status == cond.TRUE
        )
        assert bad_fresh.status_conditions().get(cond.ACTIVE).status == cond.FALSE


class TestReservedCapacityE2E:
    """reference: metricsproducer suite — exact status strings."""

    def make_mp(self, selector):
        return MetricsProducer(
            metadata=ObjectMeta(name="microservices"),
            spec=MetricsProducerSpec(
                reserved_capacity=ReservedCapacitySpec(node_selector=selector)
            ),
        )

    def test_reservation_status_strings(self, env):
        runtime, provider, clock = env
        selector = {"k8s.io/nodegroup": "group"}
        allocatable = resource_list(cpu="16300m", memory="128500Mi", pods="50")

        def node(i, labels=selector, ready="True", unschedulable=False):
            return Node(
                metadata=ObjectMeta(name=f"node-{i}", labels=dict(labels)),
                spec=NodeSpec(unschedulable=unschedulable),
                status=NodeStatus(
                    allocatable=dict(allocatable),
                    conditions=[NodeCondition("Ready", ready)],
                ),
            )

        def pod(name, node_name, cpu, memory):
            return Pod(
                metadata=ObjectMeta(name=name),
                spec=PodSpec(
                    node_name=node_name,
                    containers=[
                        Container(requests=resource_list(cpu=cpu, memory=memory))
                    ],
                ),
            )

        nodes = [
            node(0),
            node(1),
            node(2, labels={"unknown": "label"}),
            node(3),
            node(4, ready="False"),
            node(5, unschedulable=True),
        ]
        pods = [
            pod("p0", "node-0", "1100m", "1Gi"),
            pod("p1", "node-0", "2100m", "25Gi"),
            pod("p2", "node-0", "3300m", "50Gi"),
            pod("p3", "node-1", "1100m", "1Gi"),
            pod("p4", "node-2", "99", "99Gi"),  # unknown-label node: ignored
        ]
        for obj in nodes + pods:
            runtime.store.create(obj)
        runtime.store.create(self.make_mp(selector))

        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "microservices")
        assert mp.status.reserved_capacity["cpu"] == "15.54%, 7600m/48900m"
        assert mp.status.reserved_capacity["memory"] == "20.45%, 77Gi/385500Mi"
        assert mp.status.reserved_capacity["pods"] == "2.67%, 4/150"
        assert mp.status_conditions().is_happy()

        # gauges feed the autoscaler: utilization visible in the registry
        got = runtime.registry.gauge("reserved_capacity", "cpu_utilization").get(
            "microservices", "default"
        )
        assert got == pytest.approx(7.6 / 48.9)

    def test_empty_node_group_is_nan(self, env):
        runtime, provider, clock = env
        runtime.store.create(self.make_mp({"k8s.io/nodegroup": "empty"}))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "microservices")
        for resource in ("cpu", "memory", "pods"):
            assert mp.status.reserved_capacity[resource] == "NaN%, 0/0"
        assert mp.status_conditions().is_happy()


class TestScalableNodeGroupE2E:
    """reference: scalablenodegroup suite_test.go:82-124"""

    def test_scale_up_down_noop(self, env):
        runtime, provider, clock = env
        provider.node_replicas["g"] = 5
        runtime.store.create(sng_of("g", replicas=10))
        runtime.manager.reconcile_all()
        assert provider.node_replicas["g"] == 10

        sng = runtime.store.get("ScalableNodeGroup", "default", "g")
        sng.spec.replicas = 3
        runtime.store.update(sng)
        runtime.manager.reconcile_all()
        assert provider.node_replicas["g"] == 3

        clock.advance(61)
        runtime.manager.reconcile_all()  # no-op; observes the settled count
        assert provider.node_replicas["g"] == 3
        happy, fresh = all_happy(runtime.store, sng_of("g"))
        assert happy and fresh.status.replicas == 3

    def test_unstabilized_condition_propagates(self, env):
        runtime, provider, clock = env
        provider.node_replicas["g"] = 1
        provider.node_group_stable = False
        runtime.store.create(sng_of("g", replicas=1))
        runtime.manager.reconcile_all()
        sng = runtime.store.get("ScalableNodeGroup", "default", "g")
        stabilized = sng.status_conditions().get(cond.STABILIZED)
        assert stabilized.status == cond.FALSE
        assert stabilized.message == "fake factory message"
        # still Active: instability is not an error
        assert sng.status_conditions().get(cond.ACTIVE).status == cond.TRUE

    def test_unstabilized_holds_actuation(self, env):
        """No resize is issued while the group is mid-change; the next loop
        actuates once the group stabilizes (partial TPU slices are unusable,
        so overlapping resizes must never be in flight)."""
        runtime, provider, clock = env
        provider.node_replicas["g"] = 1
        provider.node_group_stable = False
        runtime.store.create(sng_of("g", replicas=5))
        runtime.manager.reconcile_all()
        assert provider.node_replicas["g"] == 1  # held
        provider.node_group_stable = True
        clock.advance(61)
        runtime.manager.reconcile_all()
        assert provider.node_replicas["g"] == 5  # actuated once stable

    def test_unstabilized_still_allows_scale_down(self, env):
        """Only scale-UPS wait for stability. A group stuck converging
        (e.g. an ASG capped below desired by a capacity shortage would
        NEVER stabilize) must accept the corrective shrink, or the
        resource deadlocks."""
        runtime, provider, clock = env
        provider.node_replicas["g"] = 5
        provider.node_group_stable = False
        runtime.store.create(sng_of("g", replicas=2))
        runtime.manager.reconcile_all()
        assert provider.node_replicas["g"] == 2  # shrink went through

    def test_retryable_error_keeps_active_flags_able_to_scale(self, env):
        runtime, provider, clock = env
        provider.node_replicas["g"] = 1
        provider.want_err = retryable_error("throttled")
        runtime.store.create(sng_of("g", replicas=2))
        runtime.manager.reconcile_all()
        sng = runtime.store.get("ScalableNodeGroup", "default", "g")
        assert sng.status_conditions().get(cond.ACTIVE).status == cond.TRUE
        able = sng.status_conditions().get(cond.ABLE_TO_SCALE)
        assert able.status == cond.FALSE
        assert "throttled" in able.message
        assert provider.node_replicas["g"] == 1  # actuation did not happen

        # provider recovers -> next loop heals everything
        provider.want_err = None
        clock.advance(61)
        runtime.manager.reconcile_all()
        runtime.manager.reconcile_all()
        happy, sng = all_happy(runtime.store, sng_of("g"))
        assert happy
        assert provider.node_replicas["g"] == 2

    def test_non_retryable_error_deactivates(self, env):
        runtime, provider, clock = env
        provider.want_err = RuntimeError("hard failure")
        runtime.store.create(sng_of("g", replicas=1))
        runtime.manager.reconcile_all()
        sng = runtime.store.get("ScalableNodeGroup", "default", "g")
        active = sng.status_conditions().get(cond.ACTIVE)
        assert active.status == cond.FALSE
        assert "hard failure" in active.message


class TestValidationGate:
    def test_invalid_resource_marked_inactive_not_crashing(self, env):
        runtime, provider, clock = env
        bad = MetricsProducer(
            metadata=ObjectMeta(name="bad"),
            spec=MetricsProducerSpec(
                reserved_capacity=ReservedCapacitySpec(node_selector={})
            ),
        )
        runtime.store.create(bad)
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "bad")
        active = mp.status_conditions().get(cond.ACTIVE)
        assert active.status == cond.FALSE
        assert "exactly one node selector" in active.message


class TestAlgorithmSelection:
    """Spec-driven algorithm selection — the seam the reference leaves as
    a TODO (algorithm.go:37-39). Custom algorithms compute per-metric
    recommendations on host; the batched kernel still applies select
    policy, stabilization, rate-limit policies, and bounds on device."""

    def test_custom_algorithm_rides_the_batch(self, env):
        from karpenter_tpu.autoscaler import algorithms

        class Fixed17:
            def get_desired_replicas(self, metric, replicas):
                return 17

        algorithms.register_algorithm("fixed17", Fixed17)
        try:
            runtime, provider, clock = env
            name = "custom-algo"
            gauge = runtime.registry.register("reserved_capacity",
                                              "cpu_utilization")
            gauge.set(name, "default", 0.85)
            provider.node_replicas[name] = 5
            runtime.store.create(sng_of(name, replicas=5))
            ha_obj = utilization_ha(name, queries=(
                "karpenter_reserved_capacity_cpu_utilization",))
            ha_obj.metadata.annotations[
                algorithms.ALGORITHM_ANNOTATION
            ] = "fixed17"
            runtime.store.create(ha_obj)

            runtime.manager.reconcile_all()
            _, ha = all_happy(runtime.store, ha_obj)
            # proportional would say 8 (0.85/0.60 * 5); fixed17 says 17,
            # and the kernel's bounds clamp [3, 23] passes it through
            assert ha.status.desired_replicas == 17
        finally:
            algorithms._registry.pop("fixed17", None)

    def test_custom_algorithm_still_bounded_by_kernel(self, env):
        from karpenter_tpu.autoscaler import algorithms

        class Huge:
            def get_desired_replicas(self, metric, replicas):
                return 1000

        algorithms.register_algorithm("huge", Huge)
        try:
            runtime, provider, clock = env
            name = "bounded-algo"
            gauge = runtime.registry.register("reserved_capacity",
                                              "cpu_utilization")
            gauge.set(name, "default", 0.5)
            provider.node_replicas[name] = 5
            runtime.store.create(sng_of(name, replicas=5))
            ha_obj = utilization_ha(name, queries=(
                "karpenter_reserved_capacity_cpu_utilization",))
            ha_obj.metadata.annotations[
                algorithms.ALGORITHM_ANNOTATION
            ] = "huge"
            runtime.store.create(ha_obj)

            runtime.manager.reconcile_all()
            _, ha = all_happy(runtime.store, ha_obj)
            assert ha.status.desired_replicas == 23  # max_replicas clamp
            unbounded = [
                c for c in ha.status.conditions
                if c.type == "ScalingUnbounded"
            ]
            assert unbounded and unbounded[0].status == "False"
        finally:
            algorithms._registry.pop("huge", None)

    def test_unknown_algorithm_rejected_at_admission(self, env):
        from karpenter_tpu.autoscaler import algorithms

        runtime, provider, clock = env
        ha_obj = utilization_ha("bad-algo")
        ha_obj.metadata.annotations[
            algorithms.ALGORITHM_ANNOTATION
        ] = "does-not-exist"
        with pytest.raises(ValueError, match="unknown algorithm"):
            ha_obj.validate()

    def test_default_rows_unchanged(self, env):
        """No annotation -> the kernel's native Proportional math; the
        canonical 85%/60%/5 -> 8 case must be untouched by the seam."""
        runtime, provider, clock = env
        name = "default-algo"
        gauge = runtime.registry.register("reserved_capacity",
                                          "cpu_utilization")
        gauge.set(name, "default", 0.85)
        provider.node_replicas[name] = 5
        runtime.store.create(sng_of(name, replicas=5))
        runtime.store.create(utilization_ha(name, queries=(
            "karpenter_reserved_capacity_cpu_utilization",)))
        runtime.manager.reconcile_all()
        _, ha = all_happy(runtime.store, utilization_ha(name))
        assert ha.status.desired_replicas == 8


class TestCurrentMetricsStatus:
    def test_status_records_last_read_metrics(self, env):
        """The reference MODELS status.currentMetrics
        (horizontalautoscaler_status.go:36-39) but never populates it;
        here every reconcile records the observed value slotted by the
        spec's target type."""
        runtime, provider, clock = env
        name = "metrics-status"
        gauge = runtime.registry.register("reserved_capacity",
                                          "cpu_utilization")
        gauge.set(name, "default", 0.85)
        provider.node_replicas[name] = 5
        runtime.store.create(sng_of(name, replicas=5))
        runtime.store.create(utilization_ha(name, queries=(
            "karpenter_reserved_capacity_cpu_utilization",)))
        runtime.manager.reconcile_all()
        _, ha = all_happy(runtime.store, utilization_ha(name))
        (status,) = ha.status.current_metrics
        assert status.prometheus.query == (
            f'karpenter_reserved_capacity_cpu_utilization{{name="{name}"}}'
        )
        assert status.prometheus.current.average_utilization == 85
        assert status.prometheus.current.value is None


# -- arbitrary scale targets (reference: autoscaler.go:196-237) -------------


@dataclass
class _WorkloadSpec:
    replicas: int = 1


@dataclass
class _WorkloadStatus:
    replicas: int = 0


@dataclass
class _Deployment:
    """A scalable kind the framework does not model: exercises the
    duck-typed scale path (spec.replicas/status.replicas) the way the
    reference's discovery + ScalesGetter reaches ANY scalable resource."""

    metadata: ObjectMeta
    spec: _WorkloadSpec
    status: _WorkloadStatus

    KIND = "Deployment"


def deployment_ha(name="web"):
    ha = utilization_ha(name, queries=(
        "karpenter_reserved_capacity_cpu_utilization",))
    ha.spec.scale_target_ref = CrossVersionObjectReference(
        api_version="apps/v1", kind="Deployment", name=name
    )
    return ha


class TestArbitraryScaleTarget:
    def test_ha_targeting_deployment_converges(self, env):
        """An HA pointing scaleTargetRef at a Deployment — legal in the
        reference via discovery+RESTMapper — actuates through the
        in-memory store's duck-typed scale subresource."""
        runtime, provider, clock = env
        name = "web"
        gauge = runtime.registry.register(
            "reserved_capacity", "cpu_utilization"
        )
        gauge.set(name, "default", 0.85)
        runtime.store.create(
            _Deployment(
                metadata=ObjectMeta(name=name),
                spec=_WorkloadSpec(replicas=5),
                status=_WorkloadStatus(replicas=5),
            )
        )
        runtime.store.create(deployment_ha(name))
        runtime.manager.reconcile_all()

        happy, ha = all_happy(runtime.store, deployment_ha(name))
        assert happy, [
            (c.type, c.status, c.message) for c in ha.status.conditions
        ]
        assert ha.status.desired_replicas == 8  # ceil(5 * 85/60)
        target = runtime.store.get("Deployment", "default", name)
        assert target.spec.replicas == 8

    def test_unscalable_kind_marks_not_active(self, env):
        """A target without spec.replicas/status.replicas does not
        implement scale: the HA row fails (Active False), nothing
        crashes."""
        runtime, provider, clock = env
        name = "cfg"

        @dataclass
        class _ConfigMap:
            metadata: ObjectMeta
            KIND = "ConfigMap"

        runtime.store.create(_ConfigMap(metadata=ObjectMeta(name=name)))
        ha = deployment_ha(name)
        ha.spec.scale_target_ref.kind = "ConfigMap"
        ha.spec.scale_target_ref.api_version = "v1"
        runtime.store.create(ha)
        runtime.manager.reconcile_all()
        fresh = runtime.store.get(
            "HorizontalAutoscaler", "default", name
        )
        conds = {c.type: c for c in fresh.status.conditions}
        assert conds["Active"].status == "False"
        assert "does not implement scale" in conds["Active"].message
