"""Cron next-match behavior (replaces robfig/cron in the reference's
scheduledcapacity producer, crontabs.go:33-73)."""

from datetime import datetime
from zoneinfo import ZoneInfo

import pytest

from karpenter_tpu.utils.cron import Cron, CronParseError

UTC = ZoneInfo("UTC")


def dt(*args, tz=UTC):
    return datetime(*args, tzinfo=tz)


class TestDefaults:
    def test_omitted_minutes_hours_mean_zero(self):
        # Pattern docs: omitted minutes/hours match 0; omitted date fields are
        # wildcards (reference: metricsproducer.go:70-83).
        c = Cron(weekdays="fri", hours="17")
        nxt = c.next_after(dt(2026, 7, 29, 12, 0))  # Wednesday
        assert nxt == dt(2026, 7, 31, 17, 0)  # Friday 17:00

    def test_all_defaults_daily_midnight(self):
        c = Cron()
        assert c.next_after(dt(2026, 7, 29, 0, 0)) == dt(2026, 7, 30, 0, 0)
        assert c.next_after(dt(2026, 7, 28, 23, 59)) == dt(2026, 7, 29, 0, 0)


class TestFields:
    def test_minute_list(self):
        c = Cron(minutes="15,45", hours="*")
        assert c.next_after(dt(2026, 1, 1, 10, 20)) == dt(2026, 1, 1, 10, 45)
        assert c.next_after(dt(2026, 1, 1, 10, 45)) == dt(2026, 1, 1, 11, 15)

    def test_weekday_names(self):
        c = Cron(weekdays="mon", hours="9")
        # 2026-07-29 is a Wednesday; next Monday is 2026-08-03
        assert c.next_after(dt(2026, 7, 29, 12, 0)) == dt(2026, 8, 3, 9, 0)

    def test_full_weekday_names_accepted(self):
        c = Cron(weekdays="monday", hours="9")
        assert c.next_after(dt(2026, 7, 29, 12, 0)) == dt(2026, 8, 3, 9, 0)

    def test_sunday_as_seven(self):
        c = Cron(weekdays="7")
        assert c.next_after(dt(2026, 7, 29, 1, 0)) == dt(2026, 8, 2, 0, 0)

    def test_month_names(self):
        c = Cron(months="dec", days="25", hours="8")
        assert c.next_after(dt(2026, 7, 29, 0, 0)) == dt(2026, 12, 25, 8, 0)

    def test_dom_and_dow_or_rule(self):
        # standard cron: both restricted -> match either
        c = Cron(days="15", weekdays="mon")
        nxt = c.next_after(dt(2026, 7, 29, 1, 0))  # Wed Jul 29
        assert nxt == dt(2026, 8, 3, 0, 0)  # Monday Aug 3 beats Aug 15

    def test_timezone(self):
        la = ZoneInfo("America/Los_Angeles")
        c = Cron(weekdays="fri", hours="17")
        now = dt(2026, 7, 31, 16, 0, tz=la)  # Friday 4pm PT
        assert c.next_after(now) == dt(2026, 7, 31, 17, 0, tz=la)

    def test_strictly_after(self):
        c = Cron(minutes="0", hours="12")
        assert c.next_after(dt(2026, 3, 1, 12, 0)) == dt(2026, 3, 2, 12, 0)


class TestErrors:
    def test_bad_element(self):
        with pytest.raises(CronParseError):
            Cron(weekdays="blursday")

    def test_garbage_after_valid_prefix_rejected(self):
        with pytest.raises(CronParseError):
            Cron(months="janet")
        with pytest.raises(CronParseError):
            Cron(weekdays="friyay")

    def test_out_of_range(self):
        with pytest.raises(CronParseError):
            Cron(hours="25")

    def test_unsatisfiable(self):
        c = Cron(days="30", months="feb")
        with pytest.raises(CronParseError):
            c.next_after(dt(2026, 1, 1, 0, 0))
