"""Fault-injection registry (karpenter_tpu/faults) + degradation-ladder
primitives (karpenter_tpu/resilience) + the engine's supervised requeue.

Chaos SCENARIOS (whole-runtime runs under seeded fault plans) live in
tests/test_chaos.py; this file pins the unit layer: plan semantics,
determinism, the instrumented injection points, breaker/backoff math,
and the engine ladder properties the satellite list names (backoff
bounded+monotone, non-retryable deactivates exactly once).
"""

from __future__ import annotations

import threading
import time

import pytest

from karpenter_tpu import faults
from karpenter_tpu.controllers.engine import Manager
from karpenter_tpu.controllers.errors import RetryableError, is_retryable
from karpenter_tpu.faults import FaultInjected, FaultRegistry
from karpenter_tpu.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    DecorrelatedJitterBackoff,
)


@pytest.fixture(autouse=True)
def _no_leaked_registry():
    """Every test leaves the process with no active fault registry."""
    yield
    faults.uninstall()


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestFaultRegistry:
    def test_inactive_is_noop(self):
        faults.inject("solver.dispatch")  # no registry: must not raise

    def test_error_plan_raises_typed_retryable(self):
        with FaultRegistry(seed=1) as reg:
            reg.plan("p", mode="error", code="Throttling")
            with pytest.raises(FaultInjected) as e:
                faults.inject("p")
            assert e.value.code == "Throttling"
            assert is_retryable(e.value)

    def test_non_retryable_error_plan(self):
        with FaultRegistry(seed=1) as reg:
            reg.plan("p", retryable=False)
            with pytest.raises(FaultInjected) as e:
                faults.inject("p")
            assert not is_retryable(e.value)

    def test_flaky_fails_first_n_then_passes_forever(self):
        with FaultRegistry(seed=1) as reg:
            plan = reg.plan("p", mode="flaky", times=3)
            for _ in range(3):
                with pytest.raises(FaultInjected):
                    faults.inject("p")
            for _ in range(10):
                faults.inject("p")  # healed
            assert plan.fired == 3
            assert plan.attempts == 13

    def test_latency_plan_sleeps(self):
        with FaultRegistry(seed=1) as reg:
            reg.plan("p", mode="latency", latency_s=0.05, times=1)
            t0 = time.perf_counter()
            faults.inject("p")
            assert time.perf_counter() - t0 >= 0.05
            faults.inject("p")  # exhausted: no sleep, no error

    def test_hang_blocks_until_released_then_raises(self):
        reg = faults.install(FaultRegistry(seed=1))
        reg.plan("p", mode="hang", times=1)
        state = {}

        def hit():
            try:
                faults.inject("p")
            except FaultInjected as e:
                state["error"] = e

        thread = threading.Thread(target=hit, daemon=True)
        thread.start()
        thread.join(timeout=0.2)
        assert thread.is_alive(), "hang plan must block the caller"
        faults.uninstall()  # releases hangs
        thread.join(timeout=5.0)
        assert not thread.is_alive()
        assert state["error"].code == "FaultHangReleased"

    def test_prefix_plan_matches_family(self):
        with FaultRegistry(seed=1) as reg:
            reg.plan("cloud.*")
            with pytest.raises(FaultInjected):
                faults.inject("cloud.get_replicas")
            with pytest.raises(FaultInjected):
                faults.inject("cloud.set_replicas")
            faults.inject("metrics.query")  # unmatched point passes

    def test_probability_sequence_is_seed_deterministic(self):
        def pattern(seed):
            reg = FaultRegistry(seed=seed)
            plan = reg.plan("p", probability=0.5)
            fired = []
            with reg:
                for _ in range(64):
                    try:
                        faults.inject("p")
                        fired.append(False)
                    except FaultInjected:
                        fired.append(True)
            assert plan.attempts == 64
            return fired

        a, b = pattern(7), pattern(7)
        assert a == b, "same seed must replay the same firing sequence"
        assert any(a) and not all(a), "p=0.5 over 64 tries fires some"
        assert pattern(8) != a, "different seed, different sequence"

    def test_counters_and_metrics_export(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry

        gauges = GaugeRegistry()
        with FaultRegistry(seed=1, registry=gauges) as reg:
            reg.plan("p", times=1)
            with pytest.raises(FaultInjected):
                faults.inject("p")
            faults.inject("p")
            faults.inject("q")
        assert reg.attempts == {"p": 2, "q": 1}
        assert reg.injected == {"p": 1}
        text = gauges.expose_text()
        assert 'karpenter_faults_attempts_total{name="p"' in text
        assert 'karpenter_faults_injected_total{name="p"' in text


# ---------------------------------------------------------------------------
# instrumented injection points
# ---------------------------------------------------------------------------


class TestInjectionPoints:
    def test_store_patch_status(self):
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.store import Store

        store = Store()
        sng = store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        with FaultRegistry(seed=1) as reg:
            reg.plan("store.patch_status", times=1)
            with pytest.raises(FaultInjected):
                store.patch_status(sng)
            store.patch_status(sng)  # exhausted: healthy again

    def test_metrics_client_query(self):
        from karpenter_tpu.api.horizontalautoscaler import (
            Metric,
            MetricTarget,
            PrometheusMetricSource,
        )
        from karpenter_tpu.metrics.clients import RegistryMetricsClient
        from karpenter_tpu.metrics.registry import GaugeRegistry

        gauges = GaugeRegistry()
        gauges.register("queue", "length").set("q", "default", 3.0)
        client = RegistryMetricsClient(gauges)
        spec = Metric(
            prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            )
        )
        assert client.get_current_value(spec).value == 3.0
        with FaultRegistry(seed=1) as reg:
            reg.plan("metrics.query")
            with pytest.raises(FaultInjected):
                client.get_current_value(spec)

    def test_fake_provider_replicas(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory

        factory = FakeFactory()
        factory.node_replicas["g"] = 4
        group = factory.node_group_for(
            type("Spec", (), {"id": "g", "type": "FakeNodeGroup"})()
        )
        with FaultRegistry(seed=1) as reg:
            reg.plan("cloud.*", times=2, code="Throttling")
            with pytest.raises(FaultInjected):
                group.get_replicas()
            with pytest.raises(FaultInjected):
                group.set_replicas(9)
            # atomic: the failed set must not have applied
            assert factory.node_replicas["g"] == 4
            assert group.get_replicas() == 4

    def test_encoder_encode(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encode_snapshot,
        )

        with FaultRegistry(seed=1) as reg:
            reg.plan("encoder.encode")
            with pytest.raises(FaultInjected):
                encode_snapshot(None, [])

    def test_constraints_mask_falls_back_to_unconstrained(self):
        """The `constraints.mask` point (docs/resilience.md): a compile
        fault degrades that encode to the unconstrained-but-feasible
        wire — operands stay None, the solve proceeds, the fallback is
        counted and the breaker FSM is fed."""
        from karpenter_tpu.api.core import (
            Container, ObjectMeta, Pod, PodSpec, resource_list,
        )
        from karpenter_tpu.constraints import ConstraintGroup
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encode_snapshot,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encoder as E,
        )
        from karpenter_tpu.ops import binpack as B
        from karpenter_tpu.store.columnar import snapshot_from_pods

        import numpy as np

        pods = [Pod(
            metadata=ObjectMeta(name="p0", labels={"t": "1"}),
            spec=PodSpec(node_name="", containers=[Container(
                requests=resource_list(cpu="1", memory="1Gi")
            )]),
        )]
        profiles = [({"cpu": 8.0, "memory": 32.0, "pods": 32.0},
                     set(), set())]
        groups = [ConstraintGroup(
            name="a", pod_selector={"t": "1"}, anti_affinity=True
        )]
        E.reset_constraint_state()
        try:
            with FaultRegistry(seed=1) as reg:
                reg.plan("constraints.mask", mode="error")
                inputs = encode_snapshot(
                    snapshot_from_pods(pods), profiles,
                    constraints=groups,
                )
            assert not B.has_constraint_operands(inputs)
            assert E.constraint_stats["fallbacks"] == 1
            assert E.constraint_stats["degraded"]
            assert E._constraint_breaker.consecutive_failures == 1
            # faults cleared: the next encode compiles the constraints
            inputs = encode_snapshot(
                snapshot_from_pods(pods), profiles, constraints=groups
            )
            assert np.asarray(inputs.pod_exclusive).any()
            assert E.constraint_stats["compiles"] == 1
            assert not E.constraint_stats["degraded"]
            assert E._constraint_breaker.consecutive_failures == 0
        finally:
            E.reset_constraint_state()

    def test_solver_dispatch_falls_back_to_numpy(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy
        from karpenter_tpu.solver import SolverService
        from test_binpack import make_inputs

        import numpy as np

        inputs = make_inputs(
            pod_requests=[[1, 1], [3, 1]], group_allocatable=[[4, 4]]
        )
        service = SolverService(
            registry=GaugeRegistry(), backend="xla",
            health_failure_threshold=100,
        )
        try:
            with FaultRegistry(seed=1) as reg:
                reg.plan("solver.dispatch", times=1)
                out = service.solve(inputs, buckets=8)
            expect = binpack_numpy(inputs, buckets=8)
            np.testing.assert_array_equal(
                np.asarray(out.assigned), np.asarray(expect.assigned)
            )
            assert service.stats.fallbacks == 1
            assert service.stats.device_failures == 1
        finally:
            service.close()

    def test_sidecar_rpc_retries_once_with_jitter(self):
        grpc = pytest.importorskip("grpc")  # noqa: F841 — client needs it
        from karpenter_tpu.sidecar.client import SolverClient

        client = SolverClient("127.0.0.1:1", retry_jitter_s=0.01)
        calls = []

        def fake_rpc(request, timeout=None):
            calls.append(timeout)
            return b"ok"

        with FaultRegistry(seed=1) as reg:
            reg.plan("sidecar.rpc", mode="flaky", times=1)
            assert client._call(fake_rpc, b"") == b"ok"
        # first attempt consumed by the injected fault, second landed
        assert calls == [client.timeout]
        # a SECOND consecutive transport failure surfaces to the caller
        with FaultRegistry(seed=1) as reg:
            reg.plan("sidecar.rpc", mode="flaky", times=2)
            with pytest.raises(FaultInjected):
                client._call(fake_rpc, b"")
        client.close()

    def test_sidecar_rpc_always_has_deadline(self):
        pytest.importorskip("grpc")
        from karpenter_tpu.sidecar.client import SolverClient

        client = SolverClient("127.0.0.1:1", timeout_seconds=0)
        seen = {}

        def fake_rpc(request, timeout=None):
            seen["timeout"] = timeout
            return b"ok"

        client._call(fake_rpc, b"")
        assert seen["timeout"] and seen["timeout"] > 0
        client.close()


# ---------------------------------------------------------------------------
# ladder primitives
# ---------------------------------------------------------------------------


class TestDecorrelatedJitterBackoff:
    def test_monotone_and_bounded(self):
        backoff = DecorrelatedJitterBackoff(base_s=1.0, cap_s=30.0, seed=3)
        prev = 0.0
        delays = []
        for _ in range(64):
            prev = backoff.next(prev)
            delays.append(prev)
        assert all(
            later >= earlier
            for earlier, later in zip(delays, delays[1:])
        ), "decorrelated-jitter ladder must never speed back up"
        assert all(1.0 <= d <= 30.0 for d in delays)
        assert delays[-1] == 30.0, "repeated failures saturate at the cap"

    def test_seeded_determinism(self):
        seq = [
            DecorrelatedJitterBackoff(seed=5).next(0.0) for _ in range(2)
        ]
        assert seq[0] == seq[1]

    def test_rejects_bad_bounds(self):
        with pytest.raises(ValueError):
            DecorrelatedJitterBackoff(base_s=10.0, cap_s=1.0)


class TestCircuitBreaker:
    def make(self, clock, threshold=3, reset=30.0):
        return CircuitBreaker(
            failure_threshold=threshold, reset_s=reset, clock=clock
        )

    def test_opens_after_threshold_then_half_open_probe(self):
        clock = FakeClock()
        breaker = self.make(clock)
        for _ in range(2):
            assert breaker.allow()
            breaker.record_failure("Throttling")
        assert breaker.state == CLOSED
        breaker.record_failure("Throttling")
        assert breaker.state == OPEN
        assert breaker.last_error_code == "Throttling"
        assert not breaker.allow(), "open circuit blocks"
        assert breaker.retry_in() == pytest.approx(30.0)
        clock.advance(31)
        assert breaker.allow(), "reset window admits the half-open probe"
        assert breaker.state == HALF_OPEN
        assert not breaker.allow(), "only ONE probe per window"
        breaker.record_success()
        assert breaker.state == CLOSED
        assert breaker.allow()

    def test_failed_probe_reopens_fresh_window(self):
        clock = FakeClock()
        breaker = self.make(clock, threshold=1, reset=10.0)
        breaker.record_failure("X")
        assert breaker.state == OPEN
        clock.advance(11)
        assert breaker.allow()
        breaker.record_failure("Y")
        assert breaker.state == OPEN
        assert not breaker.allow(), "fresh open window after failed probe"
        assert breaker.opens_total == 2

    def test_non_retryable_probe_failure_does_not_wedge_half_open(self):
        """A probe reconcile dying on a NON-retryable error must still
        record an outcome: the SNG controller records the failure before
        re-raising, so the breaker re-opens a fresh window instead of
        wedging in HALF_OPEN (where allow() is False forever and no
        probe is ever admitted again)."""
        from karpenter_tpu.cloudprovider.fake import (
            FakeFactory,
            retryable_error,
        )
        from karpenter_tpu.controllers.scalablenodegroup import (
            ScalableNodeGroupController,
        )
        from karpenter_tpu.store import Store

        clock = FakeClock()
        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        controller = ScalableNodeGroupController(
            provider, circuit_failure_threshold=2, circuit_reset_s=10.0,
            clock=clock,
        )
        store = Store()
        sng = store.create(_sng())
        provider.want_err = retryable_error("Throttling")
        controller.reconcile(sng)
        controller.reconcile(sng)  # opens
        breaker = controller._breaker(sng)
        assert breaker.state == OPEN
        clock.advance(11)
        provider.want_err = RuntimeError("hard provider bug")
        with pytest.raises(RuntimeError):
            controller.reconcile(sng)  # the half-open probe
        assert breaker.state == OPEN, "failed probe must re-open"
        assert breaker.retry_in() > 0
        clock.advance(11)
        provider.want_err = None
        controller.reconcile(sng)  # next probe heals
        assert breaker.state == CLOSED

    def test_deleted_group_prunes_breaker_state(self):
        """A recreated node group must start with a CLOSED circuit, not
        inherit the deleted group's open one (engine on_deleted hook)."""
        from karpenter_tpu.cloudprovider.fake import (
            FakeFactory,
            retryable_error,
        )
        from karpenter_tpu.controllers.scalablenodegroup import (
            ScalableNodeGroupController,
        )
        from karpenter_tpu.store import Store

        clock = FakeClock()
        provider = FakeFactory()
        provider.want_err = retryable_error("Throttling")
        controller = ScalableNodeGroupController(
            provider, circuit_failure_threshold=1, clock=clock
        )
        store = Store()
        manager = Manager(store, clock=clock).register(controller)
        store.create(_sng())
        clock.advance(10_000)
        manager.reconcile_all()
        assert controller._breaker(store.get(*self.KEY)).state == OPEN
        store.delete("ScalableNodeGroup", "default", "g")
        assert controller._breakers == {}
        provider.want_err = None
        provider.node_replicas["g"] = 1
        recreated = store.create(_sng())
        assert controller._breaker(recreated).state == CLOSED

    KEY = ("ScalableNodeGroup", "default", "g")


class TestRetryableTaxonomy:
    def test_metric_query_error_is_retryable(self):
        """A failed metric read must ride the backoff ladder, never
        deactivate the autoscaler: the metric can appear later with no
        watch event on the HA to revive it."""
        from karpenter_tpu.metrics.clients import MetricQueryError

        assert is_retryable(MetricQueryError("no metric named x"))

    def test_missing_scale_target_is_retryable(self):
        """Same posture for a missing scale target: creating the target
        fires no watch event on the HA, so deactivation would strand it."""
        from karpenter_tpu.autoscaler import BatchAutoscaler
        from karpenter_tpu.metrics.clients import MetricsClientFactory
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store import Store
        from test_chaos import queue_ha

        store = Store()
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=GaugeRegistry()), store
        )
        ha = queue_ha("missing-target", 'karpenter_queue_length{name="q"}')
        row = autoscaler._snapshot_row(ha)
        assert row.error is not None
        assert is_retryable(row.error)


# ---------------------------------------------------------------------------
# engine requeue ladder (satellite: property tests)
# ---------------------------------------------------------------------------


def _sng(name="g"):
    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )

    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name),
        spec=ScalableNodeGroupSpec(
            replicas=1, type="FakeNodeGroup", id=name
        ),
    )


class CountingController:
    """Minimal controller whose reconcile raises what the test injects."""

    def __init__(self, error_factory=None):
        self.error_factory = error_factory
        self.calls = 0

    def kind(self):
        return "ScalableNodeGroup"

    def interval(self):
        return 60.0

    def reconcile(self, obj):
        self.calls += 1
        if self.error_factory is not None:
            raise self.error_factory()


class TestEngineBackoffLadder:
    KEY = ("ScalableNodeGroup", "default", "g")

    def make(self, error_factory, cap_s=30.0):
        from karpenter_tpu.store import Store

        clock = FakeClock()
        store = Store()
        controller = CountingController(error_factory)
        manager = Manager(
            store, clock=clock, backoff_base_s=1.0, backoff_cap_s=cap_s
        ).register(controller)
        store.create(_sng())
        return manager, controller, clock

    def test_retryable_backoff_bounded_and_monotone(self):
        manager, controller, clock = self.make(
            lambda: RetryableError("throttled", code="Throttling")
        )
        delays = []
        for i in range(40):
            clock.advance(10_000)  # always past any scheduled backoff
            manager.reconcile_all()
            assert controller.calls == i + 1, "retryable keeps retrying"
            delay = manager._due[self.KEY] - clock.now
            assert 0 < delay <= 30.0, "backoff must respect the cap"
            delays.append(delay)
        assert all(
            later >= earlier
            for earlier, later in zip(delays, delays[1:])
        ), "per-object backoff must be monotone under repeated failures"
        assert delays[0] < delays[-1] == 30.0

    def test_backoff_resets_after_success(self):
        manager, controller, clock = self.make(
            lambda: RetryableError("throttled")
        )
        for _ in range(10):
            clock.advance(10_000)
            manager.reconcile_all()
        controller.error_factory = None  # dependency heals
        clock.advance(10_000)
        manager.reconcile_all()
        assert manager._due[self.KEY] - clock.now == pytest.approx(60.0), (
            "success requeues at the controller interval again"
        )
        assert self.KEY not in manager._backoff_prev

    def test_non_retryable_deactivates_exactly_once(self):
        manager, controller, clock = self.make(
            lambda: RuntimeError("poisoned spec")
        )
        for _ in range(8):
            clock.advance(10_000)
            manager.reconcile_all()
        assert controller.calls == 1, (
            "a non-retryable error must deactivate the object: exactly "
            "one reconcile, no retries"
        )
        assert manager._due[self.KEY] == float("inf")
        obj = manager.store.get(*self.KEY)
        from karpenter_tpu.api import conditions as cond

        assert (
            obj.status_conditions().get(cond.ACTIVE).status == cond.FALSE
        )

    def test_watch_event_revives_deactivated_object(self):
        manager, controller, clock = self.make(
            lambda: RuntimeError("poisoned spec")
        )
        clock.advance(10_000)
        manager.reconcile_all()
        assert controller.calls == 1
        controller.error_factory = None
        obj = manager.store.get(*self.KEY)
        obj.spec.replicas = 2  # the operator fixes the spec
        manager.store.update(obj)
        clock.advance(10_000)
        manager.reconcile_all()
        assert controller.calls == 2, "an external edit revives the object"
        assert manager._due[self.KEY] < float("inf")

    def test_failed_status_patch_requeues_with_backoff(self):
        manager, controller, clock = self.make(None)
        with FaultRegistry(seed=1) as reg:
            reg.plan("store.patch_status", times=1)
            clock.advance(10_000)
            manager.reconcile_all()  # must not raise
        delay = manager._due[self.KEY] - clock.now
        assert 0 < delay <= 30.0, "patch failure rides the backoff ladder"
        clock.advance(10_000)
        manager.reconcile_all()
        assert manager._due[self.KEY] - clock.now == pytest.approx(60.0)
