"""Seeded kill-and-restart chaos suite (ISSUE 7 acceptance).

Each scenario crashes a controller incarnation at a `process.crash`
injection point (mid-drain, mid-eviction-batch — mid-journal-write is
covered in tests/test_recovery.py), abandons it the way SIGKILL would
(no graceful checkpoint), reboots a fresh incarnation on the same
journal dir + store + provider, and runs to convergence. Pins:

  * no duplicate cloud actuations — a landed (group, count) transition
    is applied exactly once across incarnations, and a stale
    (split-brain) incarnation's replay is FENCE-REJECTED instead of
    applied;
  * eviction budgets and holds are preserved across the restart (spend
    journaled write-ahead of the evictions it covers);
  * cordoned nodes RESUME their FSM phase after the restart rather
    than being re-cordoned (or double-decrementing their group);
  * the recovery warm-up holds all disruption planning until one full
    reconcile confirms fleet state;
  * the forecast blend resumes with its earned skill and warm history
    (no cold-start reset).

`make test-recovery` runs this file + tests/test_recovery.py.
"""

import pytest

from karpenter_tpu import faults
from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    FAKE_NODE_GROUP,
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory, FakeNodeGroup
from karpenter_tpu.faults import FaultRegistry, ProcessCrash
from karpenter_tpu.runtime import KarpenterRuntime, Options
from karpenter_tpu.store import Store
from karpenter_tpu.utils.quantity import Quantity

CHAOS_SEED = 20260803


@pytest.fixture(autouse=True)
def _no_leaked_registry():
    yield
    faults.uninstall()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class RecordingNodeGroup(FakeNodeGroup):
    def set_replicas(self, count, token=None):
        super().set_replicas(count, token=token)
        self._factory.actuations.append((self._id, count))


class RecordingFactory(FakeFactory):
    """Records every SUCCESSFUL actuation: a repeated successful write
    of the same transition is a duplicate actuation."""

    def __init__(self):
        super().__init__()
        self.actuations = []

    def node_group_for(self, spec):
        return RecordingNodeGroup(self, spec.id)


def q(value):
    return Quantity.parse(str(value))


def make_node(name, cpu="8", labels=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels or {"pool": "a"})),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable={"cpu": q(cpu), "memory": q("16Gi"), "pods": q("16")},
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def make_pod(name, node=None, cpu="1", priority=None):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name=node or "",
            priority=priority,
            containers=[
                Container(requests={"cpu": q(cpu), "memory": q("1Gi")})
            ],
        ),
    )


def make_producer(ref="grp"):
    return MetricsProducer(
        metadata=ObjectMeta(name="pc"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "a"}, node_group_ref=ref
            )
        ),
    )


def make_group(name="grp", id_="grp-id", replicas=3, eviction_budget=None):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type=FAKE_NODE_GROUP, id=id_,
            eviction_budget=eviction_budget,
        ),
    )


def boot(journal_dir, store, provider, clock, **opts):
    """One controller incarnation. The store (the apiserver analog) and
    the provider (the cloud) are SHARED infrastructure that survives
    controller crashes; only the journal dir carries controller state."""
    return KarpenterRuntime(
        Options(journal_dir=str(journal_dir), **opts),
        store=store,
        cloud_provider_factory=provider,
        clock=clock,
    )


def kill(runtime):
    """SIGKILL analog: stop threads and drop the journal handle WITHOUT
    a graceful checkpoint — recovery must work from the raw journal."""
    runtime.solver_service.close()
    runtime.recovery.journal.close()


def tick(runtime, clock, advance=61.0):
    clock.advance(advance)
    runtime.manager._due = {k: 0.0 for k in runtime.manager._due}
    runtime.manager.reconcile_all()


# ---------------------------------------------------------------------------
# mid-drain crashes (consolidation)
# ---------------------------------------------------------------------------


class TestCrashMidDrain:
    def _world(self, tmp_path):
        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["grp-id"] = 3
        store.create(make_producer())
        store.create(make_group())
        for i in range(3):
            store.create(make_node(f"n{i}"))
        store.create(make_pod("p0", node="n0"))
        rt = boot(tmp_path, store, provider, clock, consolidate=True)
        return rt, store, provider, clock

    def _drive_to_draining(self, rt, clock):
        engine = rt.consolidation
        engine.plan()  # first sight starts churn clocks
        clock.advance(engine.config.cooldown_s + 1)
        engine.plan()
        assert list(engine.in_flight().values()) == ["cordoned"]
        cordoned = next(iter(engine.in_flight()))
        clock.advance(engine.config.verify_s + 1)
        return cordoned

    def test_crash_after_decrement_resumes_and_drains_exactly_once(
        self, tmp_path
    ):
        """Kill between the spec decrement and the provider actuation:
        the restarted incarnation must RESUME the draining node (not
        re-cordon it) and complete the scale-down exactly once."""
        rt1, store, provider, clock = self._world(tmp_path)
        cordoned = self._drive_to_draining(rt1, clock)
        rt1.consolidation.plan()  # APPROVED -> DRAINING + spec 3 -> 2
        assert rt1.consolidation.in_flight()[cordoned] == "draining"
        assert (
            store.get("ScalableNodeGroup", "default", "grp").spec.replicas
            == 2
        )
        assert provider.actuations == []  # provider untouched yet
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock, consolidate=True)
        try:
            # the FSM resumed: same node, same phase, still cordoned —
            # NOT re-planned from scratch
            assert rt2.consolidation.in_flight() == {cordoned: "draining"}
            node = store.get("Node", "default", cordoned)
            assert node.spec.unschedulable
            planned = rt2.registry.gauge(
                "consolidation", "drains_planned_total"
            ).get("-", "-")
            assert not planned  # no re-cordon in the new incarnation

            tick(rt2, clock)  # warm-up tick: completes the committed drain
            assert provider.node_replicas["grp-id"] == 2
            # exactly one successful provider write across BOTH
            # incarnations, stamped with the new fence generation
            assert provider.actuations == [("grp-id", 2)]
            assert provider.fence_validator.highest_seen == 2
            assert rt2.consolidation.in_flight() == {}
            names = {n.metadata.name for n in store.list("Node")}
            assert cordoned not in names  # drained node finalized
        finally:
            rt2.close()

    def test_crash_before_decrement_times_out_without_double_drain(
        self, tmp_path
    ):
        """Kill at the process.crash point INSIDE actuation (DRAINING
        journaled, scale write never issued): the restarted incarnation
        restores DRAINING — never APPROVED, so it can never decrement
        again — and the drain times out back to service with zero
        replica loss."""
        rt1, store, provider, clock = self._world(tmp_path)
        cordoned = self._drive_to_draining(rt1, clock)
        registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
        registry.plan("process.crash.drain", mode="crash", times=1)
        with pytest.raises(ProcessCrash):
            rt1.consolidation.plan()
        faults.uninstall()
        assert (
            store.get("ScalableNodeGroup", "default", "grp").spec.replicas
            == 3
        )  # the crash preceded the decrement
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock, consolidate=True)
        try:
            engine = rt2.consolidation
            assert engine.in_flight() == {cordoned: "draining"}
            tick(rt2, clock)  # warm-up
            # ride past the drain timeout: the stuck drain is vetoed and
            # the node returns to service — no decrement ever happens
            clock.advance(engine.config.drain_timeout_s + 1)
            engine.plan()
            assert engine.in_flight().get(cordoned) != "draining"
            assert provider.node_replicas["grp-id"] == 3
            assert provider.actuations == []
            sng = store.get("ScalableNodeGroup", "default", "grp")
            assert sng.spec.replicas == 3  # never double-decremented
        finally:
            rt2.close()


# ---------------------------------------------------------------------------
# mid-eviction-batch crash (preemption)
# ---------------------------------------------------------------------------


class TestCrashMidEvictionBatch:
    def _world(self, tmp_path, eviction_budget=2):
        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["grp-id"] = 2
        store.create(make_producer())
        store.create(
            make_group(replicas=2, eviction_budget=eviction_budget)
        )
        for name in ("n1", "n2"):
            store.create(make_node(name, cpu="4"))
            for i in range(4):
                store.create(
                    make_pod(f"{name}-batch-{i}", node=name, priority=0)
                )
        store.create(make_pod("critical", cpu="2", priority=1000))
        rt = boot(tmp_path, store, provider, clock, preempt=True)
        return rt, store, provider, clock

    @staticmethod
    def _bound_batch_pods(store):
        return sorted(
            p.metadata.name
            for p in store.list("Pod")
            if p.spec.node_name and "batch" in p.metadata.name
        )

    def test_budget_spend_survives_crash_mid_batch(self, tmp_path):
        """The plan needs 2 evictions against a budget of 2. Crash
        after the FIRST eviction lands: the full charge was journaled
        write-ahead, so the restarted incarnation sees the budget
        EXHAUSTED — it defers instead of evicting more, and the victim
        already evicted is never double-counted."""
        rt1, store, provider, clock = self._world(tmp_path)
        registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
        registry.plan("process.crash.evict", mode="crash", times=1)
        with pytest.raises(ProcessCrash):
            rt1.preemption.plan()
        faults.uninstall()
        survivors = self._bound_batch_pods(store)
        assert len(survivors) == 7  # exactly one victim landed pre-crash
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock, preempt=True)
        try:
            engine = rt2.preemption
            # the hold and the FULL charge (2 evictions) were restored
            assert engine.active_nodes()  # target node still held
            spent = sum(
                c.evictions
                for charges in engine._charges.values()
                for c in charges
            )
            assert spent == 2

            # warm-up: the first reconcile plans NOTHING
            tick(rt2, clock)
            assert self._bound_batch_pods(store) == survivors

            # post-warm-up planning DEFERS: the restored charge exhausts
            # the budget, so no fresh evictions happen this window
            clock.advance(engine.config.plan_interval_s + 1)
            plans = engine.plan()
            assert plans.get(("default", "critical")) is None
            assert self._bound_batch_pods(store) == survivors

            # once the restored charge expires, preemption proceeds —
            # budgets pause disruption, they don't deadlock it
            clock.advance(engine.config.hold_s + 1)
            engine.plan()
            after = self._bound_batch_pods(store)
            assert len(after) < len(survivors)
            # no zombie victims: everything evicted pre-crash stayed gone
            assert set(after) <= set(survivors)
        finally:
            rt2.close()


# ---------------------------------------------------------------------------
# split-brain: a stale incarnation replays a dead decision
# ---------------------------------------------------------------------------


class TestSplitBrainFencing:
    def test_stale_incarnation_is_fence_rejected(self, tmp_path):
        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 3
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=5, type=FAKE_NODE_GROUP, id="g"
                ),
            )
        )
        rt1 = boot(tmp_path, store, provider, clock)
        tick(rt1, clock)
        assert provider.node_replicas["g"] == 5  # gen-1 write admitted
        # rt1 "dies" (journal handle gone) but the PROCESS lingers — the
        # split-brain zombie scenario
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock)
        try:
            sng = store.get("ScalableNodeGroup", "default", "g")
            sng.spec.replicas = 6
            store.update(sng)
            tick(rt2, clock)
            assert provider.node_replicas["g"] == 6  # gen-2 admitted

            # the zombie wakes up and replays a STALE decision
            stale_ctrl = rt1.manager._controllers[1]
            zombie_view = store.get("ScalableNodeGroup", "default", "g")
            zombie_view.spec.replicas = 4
            stale_ctrl.reconcile(zombie_view)

            # the provider REJECTED the stale stamp instead of applying
            assert provider.node_replicas["g"] == 6
            assert provider.fence_validator.rejections == 1
            rejections = rt1.registry.gauge(
                "recovery", "fence_rejections_total"
            ).get("-", "-")
            assert rejections == 1.0
            # no duplicate / out-of-order actuations across incarnations
            assert provider.actuations == [("g", 5), ("g", 6)]
        finally:
            rt2.close()
            rt1.recovery = None  # journal already closed by kill()
            rt1.close()


# ---------------------------------------------------------------------------
# forecast: skill + history resume warm
# ---------------------------------------------------------------------------


class TestForecastStateSurvivesRestart:
    def test_skill_and_history_restored(self, tmp_path):
        import collections

        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        rt1 = boot(tmp_path, store, provider, clock)
        f1 = rt1.forecaster
        key = ("ha", "default", "ha", 0)
        for i in range(10):
            f1.history.append(key, clock() + i, 10.0 + i)
        # mature one pending prediction through the real scoring path,
        # earning a non-default skill EWMA (journaled as it lands)
        f1._pending[key] = collections.deque([(clock(), 20.0, 4.0)])
        f1._mature(key, ("default", "ha"), clock() + 60, actual=10.0)
        skill1 = f1.skill("default", "ha")
        assert skill1 != 1.0  # genuinely earned, not the optimistic start
        count1 = f1.history.count(key)
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock)
        try:
            f2 = rt2.forecaster
            # the blend resumes with its earned skill — no cold-start
            # reset to the optimistic 1.0
            assert f2.skill("default", "ha") == pytest.approx(skill1)
            assert f2.history.count(key) == count1
            ts1, vs1 = f1.history.series(key)
            ts2, vs2 = f2.history.series(key)
            assert list(ts2) == list(ts1)
            assert list(vs2) == list(vs1)
        finally:
            rt2.close()


# ---------------------------------------------------------------------------
# determinism: the suite is a replay, not a dice roll
# ---------------------------------------------------------------------------


class TestRestartScenarioDeterminism:
    def test_same_seed_same_world_same_outcome(self, tmp_path):
        def run(root):
            store = Store()
            clock = FakeClock()
            provider = RecordingFactory()
            provider.node_replicas["grp-id"] = 2
            store.create(make_producer())
            store.create(make_group(replicas=2, eviction_budget=2))
            for name in ("n1", "n2"):
                store.create(make_node(name, cpu="4"))
                for i in range(4):
                    store.create(
                        make_pod(f"{name}-batch-{i}", node=name, priority=0)
                    )
            store.create(make_pod("critical", cpu="2", priority=1000))
            rt1 = boot(root, store, provider, clock, preempt=True)
            with FaultRegistry(seed=CHAOS_SEED) as registry:
                registry.plan("process.crash.evict", mode="crash", times=1)
                try:
                    rt1.preemption.plan()
                except ProcessCrash:
                    pass
            kill(rt1)
            rt2 = boot(root, store, provider, clock, preempt=True)
            try:
                tick(rt2, clock)
                clock.advance(rt2.preemption.config.hold_s + 1)
                rt2.preemption.plan()
                return (
                    sorted(
                        p.metadata.name
                        for p in store.list("Pod")
                        if p.spec.node_name
                    ),
                    dict(provider.node_replicas),
                )
            finally:
                rt2.close()

        a = run(tmp_path / "a")
        b = run(tmp_path / "b")
        assert a == b


class TestReviewRegressionPins:
    def test_orphan_cordon_released_at_boot(self, tmp_path):
        """A crash between the durable cordon write and its journal
        append leaves a cordoned node with no FSM owner: the recovery
        boot must release it (uncordon), never strand it unschedulable
        forever."""
        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["grp-id"] = 2
        store.create(make_producer())
        store.create(make_group(replicas=2))
        node = make_node("n-orphan")
        node.spec.unschedulable = True
        node.metadata.annotations[
            "karpenter.sh/consolidation-state"
        ] = "cordoned"
        store.create(node)
        rt = boot(tmp_path, store, provider, clock, consolidate=True)
        try:
            refreshed = store.get("Node", "default", "n-orphan")
            assert not refreshed.spec.unschedulable
            assert (
                "karpenter.sh/consolidation-state"
                not in refreshed.metadata.annotations
            )
        finally:
            rt.close()

    def test_fence_floor_seeded_before_first_actuation(self, tmp_path):
        """A freshly booted incarnation raises the provider's fence
        floor at construction: the stale zombie is rejected even if the
        successor has not actuated anything yet."""
        store = Store()
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 3
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=5, type=FAKE_NODE_GROUP, id="g"
                ),
            )
        )
        rt1 = boot(tmp_path, store, provider, clock)
        tick(rt1, clock)
        assert provider.node_replicas["g"] == 5
        kill(rt1)

        rt2 = boot(tmp_path, store, provider, clock)  # no actuation yet
        try:
            assert provider.fence_validator.highest_seen == 2
            stale_ctrl = rt1.manager._controllers[1]
            zombie_view = store.get("ScalableNodeGroup", "default", "g")
            zombie_view.spec.replicas = 4
            stale_ctrl.reconcile(zombie_view)
            assert provider.node_replicas["g"] == 5  # not applied
            assert provider.fence_validator.rejections == 1
        finally:
            rt2.close()
