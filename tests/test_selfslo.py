"""Control-plane self-SLO monitor (observability/selfslo.py).

The acceptance pins (ISSUE 12 / docs/observability.md "Self-SLO
monitoring"):

  * multi-window burn rates over karpenter_reconcile_e2e_seconds
    (via HistogramVec.le_totals) + solver FSM + tenant breakers;
  * karpenter_selfslo_{burn_rate,budget_remaining,
    window_violations_total} publish per window, tripped 0/1;
  * a fast-burn trip records ONE selfslo_burn flight-recorder event per
    incident (trip-class: auto-dump), with hysteresis and budget
    recovery once bad events age out of the sliding windows;
  * /debug/selfslo serves the per-tenant degradation scoreboard;
  * the runtime evaluates once per manager tick (tick-hook wiring).

The 100%-solver-fault chaos acceptance lives in tests/test_chaos.py
(TestSelfSLOChaos) so it rides `make test-chaos`.
"""

import json
import urllib.request

import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import MetricsServer, SelfSLOMonitor
from karpenter_tpu.observability.flightrecorder import FlightRecorder
from karpenter_tpu.observability.selfslo import BurnWindow


class FakeClock:
    def __init__(self, start=1_000_000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def _hist(registry=None):
    registry = registry or GaugeRegistry()
    return registry.register(
        "reconcile", "e2e_seconds", kind="histogram",
        buckets=(0.1, 1.0, 10.0),
    )


class TestLeTotals:
    def test_counts_at_or_below_bound_across_series(self):
        hist = _hist()
        hist.observe("a", "-", 0.05)
        hist.observe("a", "-", 0.5)
        hist.observe("b", "-", 5.0)
        hist.observe("b", "-", 50.0)
        assert hist.le_totals(1.0) == (2, 4)
        assert hist.le_totals(10.0) == (3, 4)
        # an off-ladder bound counts conservatively (only whole buckets
        # at or below it): samples between the rung and the bound are
        # BAD, never silently good
        assert hist.le_totals(0.5) == (1, 4)


class TestBurnMath:
    def _monitor(self, **kw):
        clock = FakeClock()
        registry = GaugeRegistry()
        hist = _hist(registry)
        monitor = SelfSLOMonitor(
            registry=registry, objective_s=1.0, target=0.99,
            clock=clock, histogram=hist,
            recorder=FlightRecorder(), **kw,
        )
        return monitor, hist, clock, registry

    def test_healthy_stream_burns_nothing(self):
        monitor, hist, clock, registry = self._monitor()
        for _ in range(20):
            hist.observe("SNG", "-", 0.05)
            monitor.evaluate()
            clock.advance(10.0)
        windows = monitor._last_eval["windows"]
        assert windows["5m"]["burn_rate"] == 0.0
        assert windows["5m"]["budget_remaining"] == 1.0
        assert not monitor.tripped
        assert registry.gauge("selfslo", "burn_rate").get(
            "5m", "-"
        ) == 0.0
        assert registry.gauge("selfslo", "tripped").get(
            "-", "-"
        ) == 0.0

    def test_all_bad_stream_burns_and_publishes(self):
        monitor, hist, clock, registry = self._monitor()
        for _ in range(20):
            hist.observe("SNG", "-", 5.0)  # over the 1s objective
            monitor.evaluate()
            clock.advance(10.0)
        windows = monitor._last_eval["windows"]
        # ratio 1.0 over a 1% error budget = burn 100x
        assert windows["5m"]["burn_rate"] == pytest.approx(100.0)
        assert windows["5m"]["budget_remaining"] == 0.0
        assert registry.gauge(
            "selfslo", "window_violations_total"
        ).get("5m", "-") >= 1.0

    def test_fsm_and_tenant_sources_feed_bad_events(self):
        fsm = {"state": "degraded"}
        tenants = {"t1": True, "t2": False}
        monitor, hist, clock, _ = self._monitor(
            fsm_source=lambda: fsm["state"],
            tenant_source=lambda: tenants,
        )
        for _ in range(5):
            monitor.evaluate()  # no e2e samples at all
            clock.advance(10.0)
        windows = monitor._last_eval["windows"]
        # per evaluation: fsm bad + t1 bad + t2 good = 2 bad / 3 total
        assert windows["5m"]["bad"] == 10
        assert windows["5m"]["total"] == 15
        assert windows["5m"]["burn_rate"] > 14.4

    def test_source_failures_never_raise(self):
        def broken():
            raise RuntimeError("source down")

        monitor, hist, clock, _ = self._monitor(
            fsm_source=broken, tenant_source=broken
        )
        result = monitor.evaluate()
        assert result["windows"]["5m"]["total"] == 0
        board = monitor.scoreboard()
        assert board["solver_backend"] == "unknown"
        assert board["tenants"] == {}


class TestTripLifecycle:
    def test_trip_dump_hysteresis_and_recovery(self, tmp_path):
        clock = FakeClock()
        registry = GaugeRegistry()
        hist = _hist(registry)
        recorder = FlightRecorder(dump_dir=str(tmp_path))
        fsm = {"state": "healthy"}
        monitor = SelfSLOMonitor(
            registry=registry, objective_s=1.0, target=0.99,
            clock=clock, histogram=hist,
            fsm_source=lambda: fsm["state"], recorder=recorder,
        )
        for _ in range(30):
            hist.observe("SNG", "-", 0.05)
            monitor.evaluate()
            clock.advance(10.0)
        assert not monitor.tripped
        fsm["state"] = "degraded"
        for _ in range(40):
            monitor.evaluate()
            clock.advance(10.0)
        assert monitor.tripped
        assert monitor.trips_total == 1
        burns = [
            e for e in recorder.events() if e["kind"] == "selfslo_burn"
        ]
        assert len(burns) == 1, "one incident, one burn event"
        assert burns[0]["burn_fast"] > 14.4
        # trip-class kind: the ring auto-dumped crash-safely
        dumps = [
            p.name for p in tmp_path.iterdir()
            if "selfslo_burn" in p.name
        ]
        assert dumps, "selfslo_burn must auto-dump the ring"
        assert registry.gauge("selfslo", "tripped").get(
            "-", "-"
        ) == 1.0
        # faults clear: the fast window slides clean, budget recovers,
        # the trip re-arms — and NO second event fired meanwhile
        fsm["state"] = "healthy"
        for _ in range(60):
            hist.observe("SNG", "-", 0.05)
            monitor.evaluate()
            clock.advance(10.0)
        assert not monitor.tripped
        windows = monitor._last_eval["windows"]
        assert windows["5m"]["burn_rate"] == 0.0
        assert windows["5m"]["budget_remaining"] == 1.0
        assert monitor.trips_total == 1

    def test_custom_windows_and_bad_target_rejected(self):
        with pytest.raises(ValueError):
            SelfSLOMonitor(target=1.5)
        monitor = SelfSLOMonitor(
            windows=(
                BurnWindow("1m", 60.0, 2.0),
                BurnWindow("10m", 600.0, 2.0),
            ),
            clock=FakeClock(),
        )
        assert monitor.evaluate()["windows"].keys() == {"1m", "10m"}


class TestScoreboardEndpoint:
    def test_debug_selfslo_serves_scoreboard(self):
        clock = FakeClock()
        tenants = {"alpha": True, "beta": False}
        monitor = SelfSLOMonitor(
            clock=clock,
            fsm_source=lambda: "healthy",
            tenant_source=lambda: tenants,
            recorder=FlightRecorder(),
        )
        monitor.evaluate()
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1", selfslo=monitor
        )
        port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/selfslo", timeout=5
            ) as resp:
                body = json.loads(resp.read())
            assert body["enabled"] is True
            assert body["solver_backend"] == "healthy"
            assert body["tenants"]["alpha"]["breaker_open"] is True
            assert body["tenants"]["beta"]["degraded"] is False
            assert "5m" in body["windows"]
        finally:
            server.stop()

    def test_debug_selfslo_without_monitor(self):
        server = MetricsServer(GaugeRegistry(), port=0, host="127.0.0.1")
        port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/selfslo", timeout=5
            ) as resp:
                assert json.loads(resp.read()) == {"enabled": False}
        finally:
            server.stop()


class TestRuntimeWiring:
    def test_manager_tick_evaluates_monitor(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        runtime = KarpenterRuntime(
            Options(selfslo_objective_s=2.5, selfslo_target=0.95),
            cloud_provider_factory=FakeFactory(),
        )
        try:
            assert runtime.selfslo.objective_s == 2.5
            assert runtime.selfslo.target == 0.95
            runtime.manager.reconcile_all()
            runtime.manager.reconcile_all()
            # evaluated per tick: gauges live in THIS registry
            assert runtime.registry.gauge("selfslo", "burn_rate").get(
                "5m", "-"
            ) is not None
            board = runtime.selfslo.scoreboard()
            assert board["solver_backend"] == "healthy"
            assert board["at"] is not None
        finally:
            runtime.close()
